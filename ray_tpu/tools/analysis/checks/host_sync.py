"""host-sync-in-hot-path: device->host round trips inside registered hot paths.

The single biggest perf bug in this repo's history — the ~110 ms host round
trip that capped engine decode at 55.8 tok/s until PR 12 — was a host sync
on the scheduler hot path that no review caught. Hot functions are now
registered explicitly with `@hot_path` (ray_tpu/util/hot_path.py, a runtime
no-op), and this check walks them PLUS their one-level same-file callees for
constructs that force the host to wait on the device:

- ``.item()`` / ``.tolist()`` on anything;
- ``block_until_ready`` (call or attribute);
- ``np.asarray(...)`` / ``numpy.asarray(...)`` / ``jax.device_get(...)``;
- ``float(x)`` / ``int(x)`` / ``bool(x)`` where ``x`` is a bare
  name/attribute/subscript (scalarizing an array implicitly calls
  ``__float__``/``__index__`` — a blocking transfer when x lives on device).

The designed sync points (the engine's one fetch per K-step burst) carry an
inline allow with the reason spelling out why the sync is intentional.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..base import Check, Project, SourceFile, Violation, call_name, decorator_names

SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SCALARIZERS = {"float", "int", "bool"}


def _hot_roots(tree: ast.AST) -> List[ast.AST]:
    """Functions decorated @hot_path (bare or called form)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in decorator_names(node):
                if dec == "hot_path" or dec.endswith(".hot_path"):
                    out.append(node)
    return out


def _local_defs(tree: ast.AST) -> Dict[str, ast.AST]:
    """name -> def for module-level functions and every method (methods keyed
    as 'ClassName.method' AND bare 'method' for self-call resolution)."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs[f"{node.name}.{item.name}"] = item
                    defs.setdefault(item.name, item)
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def _callees(fn: ast.AST) -> Set[str]:
    """Names this function calls that can resolve in-file: `self.m()` -> 'm',
    bare `helper()` -> 'helper'."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            out.add(func.id)
        elif (isinstance(func, ast.Attribute)
              and isinstance(func.value, ast.Name)
              and func.value.id in ("self", "cls")):
            out.add(func.attr)
    return out


def _sync_sites(fn: ast.AST) -> Iterable[Tuple[int, str]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node.func)
            if name in SYNC_CALLS:
                yield node.lineno, f"{name}() copies device memory to host"
                continue
            last = name.rsplit(".", 1)[-1]
            if last in SYNC_METHODS and "." in name:
                yield node.lineno, (f".{last}() blocks on the device "
                                    "round trip")
                continue
            if (name in SCALARIZERS and len(node.args) == 1
                    and not node.keywords
                    and isinstance(node.args[0],
                                   (ast.Name, ast.Attribute, ast.Subscript))):
                yield node.lineno, (f"{name}() on a name scalarizes (implicit "
                                    "__float__/__index__ host sync if the "
                                    "value is a device array)")


class HostSyncInHotPath(Check):
    name = "host-sync-in-hot-path"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        roots = _hot_roots(f.tree)
        if not roots:
            return
        defs = _local_defs(f.tree)
        seen: Set[int] = set()
        for root in roots:
            targets = [(root, root.name)]
            for callee in sorted(_callees(root)):
                fn = defs.get(callee)
                if fn is not None and fn not in roots:
                    targets.append((fn, f"{root.name} -> {callee}"))
            for fn, label in targets:
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                for line, why in _sync_sites(fn):
                    yield Violation(
                        self.name, f.path, line,
                        f"host sync on hot path {label}: {why}")
