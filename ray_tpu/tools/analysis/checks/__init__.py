"""graftlint checks, one module per project invariant."""
from __future__ import annotations

from typing import List

from ..base import Check
from .blocking_control import BlockingControlPath, UnboundedReconnect
from .host_sync import HostSyncInHotPath
from .knob_registry import KnobRegistry
from .no_print import NoPrint
from .swallowed_exception import SwallowedException
from .thread_hygiene import LockHygiene, ThreadHygiene

ALL_CHECKS: List[Check] = [
    SwallowedException(),
    HostSyncInHotPath(),
    BlockingControlPath(),
    UnboundedReconnect(),
    KnobRegistry(),
    ThreadHygiene(),
    LockHygiene(),
    NoPrint(),
]

CHECK_NAMES = [c.name for c in ALL_CHECKS]
