"""no-print: runtime code logs through LOGGER, never print().

print() bypasses log levels, per-process capture, and the driver's log
fan-in — and tears mid-line tqdm bars (the telemetry convention finalizes a
bar with `tqdm_ray.ensure_newline()` before logging for exactly that
reason). The CLI (`ray_tpu/scripts/`) and the progress-bar renderer
(`experimental/tqdm_ray.py`) own their stdout by design and are out of
scope; everything else needs LOGGER or an inline allow with a reason (e.g.
`Dataset.show()`, whose contract IS printing rows to the console).
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..base import Check, Project, SourceFile, Violation

SKIP_PARTS = ("ray_tpu/scripts/", "experimental/tqdm_ray.py", "test_utils.py",
              "ray_tpu/tools/")  # lint/doc tooling reports on stdout by design


class NoPrint(Check):
    name = "no-print"

    def skip(self, path: str) -> bool:
        return any(part in path for part in SKIP_PARTS)

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield Violation(
                    self.name, f.path, node.lineno,
                    "print() in runtime code — use the module LOGGER "
                    "(throttled if it can repeat; ensure_newline() first if "
                    "a tqdm bar may be mid-line)")
