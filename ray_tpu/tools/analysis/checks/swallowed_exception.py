"""swallowed-exception: broad except handlers that silently eat the error.

The project convention (PR 8/10): an `except:` / `except Exception:` body
must re-raise, log through a LOGGER (throttled where it can repeat), or at
minimum DO something with the caught exception object. A handler that
catches everything and uses none of it is how the engine lost real failures
behind `pass` 164 times — silence is only acceptable with an inline
`# graftlint: allow[swallowed-exception] reason`.

A handler counts as NOT silent when its body contains any of:

- a `raise` (re-raise or wrap);
- a call whose dotted target looks like logging (`logger.warning`,
  `LOGGER.exception`, `logging.error`, `self._logger.info`, ...) or
  `traceback.print_exc` / `sys.exit` / `os._exit`;
- any read of the caught exception name (``except Exception as e`` followed
  by a use of ``e`` — wrapped, stored, reported somewhere).
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..base import Check, Project, SourceFile, Violation, call_name

BROAD = {"Exception", "BaseException"}
LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log"}
EXIT_CALLS = {"traceback.print_exc", "sys.exit", "os._exit"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _is_log_call(node: ast.Call) -> bool:
    name = call_name(node.func)
    if name in EXIT_CALLS:
        return True
    parts = name.split(".")
    if len(parts) >= 2 and parts[-1] in LOG_METHODS:
        receiver = parts[-2].lower()
        if "log" in receiver:
            return True
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    caught = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _is_log_call(node):
            return True
        if (caught and isinstance(node, ast.Name) and node.id == caught
                and isinstance(node.ctx, ast.Load)):
            return True
    return False


class SwallowedException(Check):
    name = "swallowed-exception"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handles(node):
                continue
            what = ("bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}")
            yield Violation(
                self.name, f.path, node.lineno,
                f"{what} swallows the error silently: re-raise, log via "
                "LOGGER (throttled if it can repeat), or use the caught "
                "exception")
