"""knob-registry: every RAY_TPU_* knob registered, referenced, and documented.

`ray_tpu/knobs.py` is the single source of truth (name, type, default, doc,
owning subsystem). This check enforces, without importing the runtime:

- **unregistered**: an exact ``RAY_TPU_*`` string literal anywhere in the
  tree that names no registry entry (an env read the registry doesn't know,
  or a typo'd knob name);
- **stale**: a non-internal registry entry whose env name appears nowhere
  outside the registry and whose CONFIG attr is never referenced — a knob
  nothing reads anymore;
- **README drift**: the generated knob tables in README.md (between
  ``<!-- knobs:<subsystem> -->`` markers) differ from what the registry
  renders, or a subsystem has no generated table at all. Fix with
  ``ray-tpu lint --write-docs``.

The registry module is stdlib-only by design and is loaded as a DETACHED
module straight from its file path — `import ray_tpu` never happens here.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import Iterable, Optional

from ..base import Check, Project, SourceFile, Violation

_KNOBS_REL = "knobs.py"  # relative to the ray_tpu package dir


def load_knobs(pkg_dir: str):
    """Load ray_tpu/knobs.py as a detached stdlib-only module."""
    path = os.path.join(pkg_dir, _KNOBS_REL)
    name = "_graftlint_knobs"
    cached = sys.modules.get(name)
    if cached is not None and getattr(cached, "__file__", None) == path:
        return cached
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolves cls.__module__ through here
    spec.loader.exec_module(mod)
    return mod


class KnobRegistry(Check):
    name = "knob-registry"

    def __init__(self, readme: Optional[str] = None):
        # repo-relative README path; None disables the drift check (fixtures)
        self.readme = readme if readme is not None else "README.md"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        return ()  # everything is cross-file; see run_project

    def _pkg_dir(self, project: Project) -> Optional[str]:
        for f in project.files:
            if f.path.endswith(f"ray_tpu/{_KNOBS_REL}") or f.path == _KNOBS_REL:
                return os.path.dirname(os.path.join(project.root, f.path))
        return None

    def run_project(self, project: Project) -> Iterable[Violation]:
        pkg_dir = self._pkg_dir(project)
        if pkg_dir is None:
            return  # no registry in the analyzed set (fixture runs)
        knobs = load_knobs(pkg_dir)
        registry_paths = {
            os.path.relpath(os.path.join(pkg_dir, _KNOBS_REL), project.root)
            .replace(os.sep, "/")}

        # -- unregistered literals
        for env, sites in sorted(project.env_literals.items()):
            if env in knobs.REGISTRY:
                continue
            for path, line in sites:
                if path in registry_paths:
                    continue
                yield Violation(
                    self.name, path, line,
                    f"{env} is not registered in ray_tpu/knobs.py (add a "
                    "Knob entry with type/default/doc/subsystem, or fix the "
                    "name)")

        # -- stale registry entries
        knobs_rel = next(iter(registry_paths))
        knobs_file = project.by_path.get(knobs_rel)
        for k in knobs.KNOBS:
            used_env = any(path not in registry_paths
                           for path, _ in project.env_literals.get(k.env, ()))
            used_attr = k.attr is not None and (
                k.attr in project.attr_names or k.attr in project.str_constants)
            if used_env or used_attr or k.internal:
                continue
            if k.subsystem == "bench":
                # read by the repo-root bench drivers (core_bench.py & co),
                # which live outside the analyzed package tree
                continue
            line = 1
            if knobs_file is not None:
                for idx, text in enumerate(knobs_file.lines, start=1):
                    if f'"{k.env}"' in text:
                        line = idx
                        break
            yield Violation(
                self.name, knobs_rel, line,
                f"{k.env} is registered but nothing references it anymore "
                "(drop the entry or wire the knob back up)")

        # -- README drift
        if self.readme is None:
            return
        readme_abs = os.path.join(project.root, self.readme)
        if not os.path.exists(readme_abs):
            return
        with open(readme_abs, encoding="utf-8") as fh:
            text = fh.read()
        regenerated = knobs.generate_readme(text)
        if regenerated != text:
            yield Violation(
                self.name, self.readme, 1,
                "generated knob tables are stale — run "
                "`ray-tpu lint --write-docs`")
        for sub in knobs.SUBSYSTEMS:
            if f"<!-- knobs:{sub} " not in text:
                yield Violation(
                    self.name, self.readme, 1,
                    f"subsystem {sub!r} has no generated knob table in the "
                    "README (add a `<!-- knobs:" + sub + " ... -->` block "
                    "and run `ray-tpu lint --write-docs`)")
