"""blocking-control-path: blocking calls where the control plane must stay live.

The PR 11 lesson: a saturated replica must still answer the controller, so
drain/health/arm RPCs ride a dedicated "control" actor concurrency group —
and nothing on that group (or in an async handler) may block the thread on
sleeps, object fetches, or socket reads. Control contexts are:

- ``async def`` functions anywhere in the runtime (the event loop stalls for
  every other coroutine while a blocking call runs);
- actor methods declared ``concurrency_group="control"`` (the dedicated
  control lane must never wait behind data-plane work);
- functions explicitly registered with ``@control_path``
  (ray_tpu/util/hot_path.py) — health probes and drain paths that are
  control-plane by contract even off a concurrency group.

Flagged calls: ``time.sleep``, ``ray_tpu.get`` / ``ray_tpu.wait``,
``subprocess.run/check_call/check_output``, socket/pipe reads
(``.recv``/``.recv_bytes``/``.recv_bytes_into``/``.accept``), and
``.result()`` on futures. In async code the non-blocking spelling exists
(``await asyncio.sleep``, executors); on the control group the work belongs
on another group.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from ..base import Check, Project, SourceFile, Violation, call_name

BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the thread (asyncio.sleep / move off "
                  "the control group)",
    "ray_tpu.get": "ray_tpu.get blocks on object resolution",
    "ray_tpu.wait": "ray_tpu.wait blocks on object resolution",
    "subprocess.run": "subprocess.run blocks on the child",
    "subprocess.check_call": "subprocess.check_call blocks on the child",
    "subprocess.check_output": "subprocess.check_output blocks on the child",
}
BLOCKING_METHODS = {
    "recv": "socket/pipe recv blocks until the peer sends",
    "recv_bytes": "pipe recv_bytes blocks until the peer sends",
    "recv_bytes_into": "pipe recv_bytes_into blocks until the peer sends",
    "accept": "accept blocks until a peer connects",
    "result": "Future.result blocks until completion",
}


def _control_contexts(tree: ast.AST) -> List[Tuple[ast.AST, str]]:
    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            out.append((node, f"async def {node.name}"))
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = call_name(target)
                if name == "control_path" or name.endswith(".control_path"):
                    out.append((node, f"@control_path {node.name}"))
                    break
                if isinstance(dec, ast.Call):
                    for kw in dec.keywords:
                        if (kw.arg == "concurrency_group"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value == "control"):
                            out.append(
                                (node, f'control-group method {node.name}'))
                            break
                    else:
                        continue
                    break
    return out


def _nested_defs(fn: ast.AST) -> set:
    """ids of function defs nested inside fn (their bodies are NOT part of
    this control context — a sync helper defined here may run elsewhere)."""
    nested = set()
    for node in ast.walk(fn):
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                nested.add(id(sub))
    return nested


class BlockingControlPath(Check):
    name = "blocking-control-path"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for fn, label in _control_contexts(f.tree):
            nested = _nested_defs(fn)
            for node in ast.walk(fn):
                if id(node) in nested or not isinstance(node, ast.Call):
                    continue
                name = call_name(node.func)
                if name in BLOCKING_EXACT:
                    yield Violation(self.name, f.path, node.lineno,
                                    f"{BLOCKING_EXACT[name]} (in {label})")
                    continue
                last = name.rsplit(".", 1)[-1]
                if last in BLOCKING_METHODS and "." in name:
                    yield Violation(
                        self.name, f.path, node.lineno,
                        f"{BLOCKING_METHODS[last]} (in {label})")
