"""blocking-control-path: blocking calls where the control plane must stay live.

The PR 11 lesson: a saturated replica must still answer the controller, so
drain/health/arm RPCs ride a dedicated "control" actor concurrency group —
and nothing on that group (or in an async handler) may block the thread on
sleeps, object fetches, or socket reads. Control contexts are:

- ``async def`` functions anywhere in the runtime (the event loop stalls for
  every other coroutine while a blocking call runs);
- actor methods declared ``concurrency_group="control"`` (the dedicated
  control lane must never wait behind data-plane work);
- functions explicitly registered with ``@control_path``
  (ray_tpu/util/hot_path.py) — health probes and drain paths that are
  control-plane by contract even off a concurrency group.

Flagged calls: ``time.sleep``, ``ray_tpu.get`` / ``ray_tpu.wait``,
``subprocess.run/check_call/check_output``, socket/pipe reads
(``.recv``/``.recv_bytes``/``.recv_bytes_into``/``.accept``), and
``.result()`` on futures. In async code the non-blocking spelling exists
(``await asyncio.sleep``, executors); on the control group the work belongs
on another group.

The sibling ``unbounded-reconnect`` check (PR 18's head-death lesson) guards
the other control-path liveness invariant: every reconnect loop must be
BOUNDED. A ``while True`` that redials forever turns a dead head into a
silent hang — the caller never gets the typed HeadUnavailableError that lets
degraded-mode serving and the chaos gate reason about the outage. Flagged:
a constant-true ``while`` whose body establishes connections (``dial`` /
``connect`` / ``create_connection`` / ``HeadConnection`` / ``Client``) with
no deadline/attempt bound in sight (no comparison against a deadline,
timeout, attempt, retry, or budget value anywhere in the loop).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from ..base import Check, Project, SourceFile, Violation, call_name

BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the thread (asyncio.sleep / move off "
                  "the control group)",
    "ray_tpu.get": "ray_tpu.get blocks on object resolution",
    "ray_tpu.wait": "ray_tpu.wait blocks on object resolution",
    "subprocess.run": "subprocess.run blocks on the child",
    "subprocess.check_call": "subprocess.check_call blocks on the child",
    "subprocess.check_output": "subprocess.check_output blocks on the child",
}
BLOCKING_METHODS = {
    "recv": "socket/pipe recv blocks until the peer sends",
    "recv_bytes": "pipe recv_bytes blocks until the peer sends",
    "recv_bytes_into": "pipe recv_bytes_into blocks until the peer sends",
    "accept": "accept blocks until a peer connects",
    "result": "Future.result blocks until completion",
}


def _control_contexts(tree: ast.AST) -> List[Tuple[ast.AST, str]]:
    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            out.append((node, f"async def {node.name}"))
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = call_name(target)
                if name == "control_path" or name.endswith(".control_path"):
                    out.append((node, f"@control_path {node.name}"))
                    break
                if isinstance(dec, ast.Call):
                    for kw in dec.keywords:
                        if (kw.arg == "concurrency_group"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value == "control"):
                            out.append(
                                (node, f'control-group method {node.name}'))
                            break
                    else:
                        continue
                    break
    return out


def _nested_defs(fn: ast.AST) -> set:
    """ids of function defs nested inside fn (their bodies are NOT part of
    this control context — a sync helper defined here may run elsewhere)."""
    nested = set()
    for node in ast.walk(fn):
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                nested.add(id(sub))
    return nested


class BlockingControlPath(Check):
    name = "blocking-control-path"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for fn, label in _control_contexts(f.tree):
            nested = _nested_defs(fn)
            for node in ast.walk(fn):
                if id(node) in nested or not isinstance(node, ast.Call):
                    continue
                name = call_name(node.func)
                if name in BLOCKING_EXACT:
                    yield Violation(self.name, f.path, node.lineno,
                                    f"{BLOCKING_EXACT[name]} (in {label})")
                    continue
                last = name.rsplit(".", 1)[-1]
                if last in BLOCKING_METHODS and "." in name:
                    yield Violation(
                        self.name, f.path, node.lineno,
                        f"{BLOCKING_METHODS[last]} (in {label})")


# connection-establishing call names (last dotted segment / bare constructor)
CONNECT_CALLS = {"dial", "_dial", "connect", "connect_ex", "create_connection",
                 "open_connection"}
CONNECT_CTORS = {"Client", "HeadConnection", "SecureClient"}

# an identifier mentioning one of these inside a comparison is taken as
# evidence the loop is bounded (deadline check, attempt budget, ...)
_BOUND_HINTS = ("deadline", "timeout", "attempt", "retr", "tries", "budget",
                "remaining", "expire", "monotonic", "time")


def _const_true(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value) is True


def _mentions_bound(node: ast.AST) -> bool:
    """True when the comparison references a deadline/attempt-flavored value
    (by variable name, attribute, or a time.monotonic()/time.time() call)."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            low = name.lower()
            if any(h in low for h in _BOUND_HINTS):
                return True
    return False


class UnboundedReconnect(Check):
    """Flag `while True` loops that establish connections with no visible
    deadline or attempt bound — the retry must be bounded so a dead peer
    surfaces as a typed error instead of a hang."""

    name = "unbounded-reconnect"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for loop in ast.walk(f.tree):
            if not isinstance(loop, ast.While) or not _const_true(loop.test):
                continue
            # nested function bodies run elsewhere; nested constant-true
            # whiles get their own visit — exclude both from this loop's scan
            skip = set()
            for child in ast.iter_child_nodes(loop):
                for sub in ast.walk(child):
                    if sub is not child and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        for inner in ast.walk(sub):
                            skip.add(id(inner))
            connects: List[ast.Call] = []
            bounded = False
            for child in loop.body:
                for sub in ast.walk(child):
                    if id(sub) in skip:
                        continue
                    if isinstance(sub, ast.Call):
                        name = call_name(sub.func)
                        last = name.rsplit(".", 1)[-1]
                        if (last in CONNECT_CALLS and "." in name) \
                                or last in CONNECT_CTORS:
                            connects.append(sub)
                    elif isinstance(sub, ast.Compare) and _mentions_bound(sub):
                        bounded = True
                    elif isinstance(sub, ast.While) and not _const_true(sub.test) \
                            and _mentions_bound(sub.test):
                        bounded = True
            if connects and not bounded:
                yield Violation(
                    self.name, f.path, connects[0].lineno,
                    "reconnect loop with no deadline/attempt bound: a dead "
                    "peer must surface as a typed error, not an infinite "
                    "redial (compare against a deadline or attempt budget, "
                    "or hoist the dial into a bounded helper)")
