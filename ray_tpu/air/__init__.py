"""ray_tpu.air — shared configuration for Train/Tune (reference: python/ray/air/)."""
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig  # noqa: F401
