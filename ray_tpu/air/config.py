"""Shared AIR-style run configuration.

Reference capability: python/ray/air/config.py — ScalingConfig (:98), FailureConfig (:320),
CheckpointConfig (:370), RunConfig (:519). TPU-native twist: ScalingConfig speaks chips and
pod-slice topologies, not GPUs.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one holds.

    On TPU, `num_workers` is the number of *host processes* (one per TPU VM host);
    `chips_per_worker` is the accelerator count each host contributes to the global mesh.
    `use_tpu=False` gives CPU workers (tests, data-only jobs).
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: float = 0.0
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-16": schedule workers onto one slice

    @property
    def total_workers(self) -> int:
        return self.num_workers

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", self.cpus_per_worker)
        if self.use_tpu or self.chips_per_worker:
            res.setdefault("TPU", self.chips_per_worker or 1.0)
        return res


@dataclass
class FailureConfig:
    """Reference air/config.py:320. max_failures: worker-group restarts allowed; <0 = infinite."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference air/config.py:370. Top-k retention ordered by a reported metric."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"
    checkpoint_frequency: int = 1
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Reference air/config.py:519."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[dict] = None  # stop criteria, e.g. {"training_iteration": 10}
    verbose: int = 1
