"""RemoteFunction: the @ray_tpu.remote task API.

Capability parity: reference python/ray/remote_function.py (RemoteFunction:41, _remote:308).
Functions are cloudpickled once, registered in the cluster function table keyed by content
hash, and referenced by id afterwards (reference: function_manager.py export via GCS KV).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from . import global_state
from .ids import ObjectID, TaskID
from .object_ref import ObjectRef
from .object_store import _inline_threshold
from .task_spec import TaskSpec, _RefMarker

_DEFAULT_TASK_OPTIONS = dict(
    num_cpus=1.0,
    num_tpus=0.0,
    resources=None,
    num_returns=1,
    max_retries=None,  # resolved from CONFIG.task_max_retries at decoration
    retry_exceptions=False,
    scheduling_strategy="DEFAULT",
    name=None,
    runtime_env=None,
)


def compute_fn_id(fn_bytes: bytes) -> bytes:
    return hashlib.sha256(fn_bytes).digest()[:16]


def build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    return res


def encode_args(ctx, args, kwargs):
    """Split top-level ObjectRef args out for pre-dispatch resolution; auto-put large args.

    Returns (meta, arg_refs, pins). `pins` are owned refs created by auto-put; the caller
    MUST keep them alive until ctx.submit() has pinned the args in the task manager,
    otherwise their __del__ frees the object before dispatch.
    """
    arg_refs = []
    pins = []

    def enc(a):
        if isinstance(a, ObjectRef):
            m = _RefMarker(len(arg_refs))
            arg_refs.append(a.id)
            return m
        return a

    proc_args = [enc(a) for a in args]
    proc_kwargs = {k: enc(v) for k, v in kwargs.items()}
    meta = cloudpickle.dumps((proc_args, proc_kwargs), protocol=5)
    if len(meta) > _inline_threshold():
        # Move every non-trivial argument through the object store (zero-copy shm)
        # instead of copying it through the control pipe with every dispatch.
        def enc_big(a):
            if isinstance(a, _RefMarker):
                return a
            if _rough_size(a) > 4096:
                ref = ctx.put(a)
                pins.append(ref)
                m = _RefMarker(len(arg_refs))
                arg_refs.append(ref.id)
                return m
            return a

        proc_args = [enc_big(a) for a in proc_args]
        proc_kwargs = {k: enc_big(v) for k, v in proc_kwargs.items()}
        meta = cloudpickle.dumps((proc_args, proc_kwargs), protocol=5)
    return meta, arg_refs, pins


def _rough_size(a) -> int:
    try:
        import numpy as np

        if isinstance(a, np.ndarray):
            return a.nbytes
    # graftlint: allow[swallowed-exception] size probe over arbitrary user objects; falls through to the next estimator
    except Exception:
        pass
    try:
        return len(a)
    except TypeError:
        return 0


_registered_fns: set = set()


def register_function(ctx, fn_id: bytes, fn_bytes: bytes) -> None:
    key = (id(ctx), fn_id)
    if key not in _registered_fns:
        ctx.register_fn(fn_id, fn_bytes)
        _registered_fns.add(key)


class RemoteFunction:
    def __init__(self, fn, **options):
        self._fn = fn
        self._options = {**_DEFAULT_TASK_OPTIONS, **options}
        if self._options.get("max_retries") is None:
            from ray_tpu.config import CONFIG

            self._options["max_retries"] = CONFIG.task_max_retries
        self._fn_bytes: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        self.__name__ = getattr(fn, "__name__", "anonymous")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _ensure_pickled(self):
        if self._fn_bytes is None:
            self._fn_bytes = cloudpickle.dumps(self._fn)
            self._fn_id = compute_fn_id(self._fn_bytes)
        return self._fn_id, self._fn_bytes

    def options(self, **options) -> "RemoteFunction":
        rf = RemoteFunction(self._fn, **{**self._options, **options})
        rf._fn_bytes = self._fn_bytes
        rf._fn_id = self._fn_id
        return rf

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, opts):
        from ray_tpu.runtime_env import resolved_runtime_env as _renv

        ctx = global_state.worker()
        fn_id, fn_bytes = self._ensure_pickled()
        register_function(ctx, fn_id, fn_bytes)
        meta, arg_refs, pins = encode_args(ctx, args, kwargs)
        num_returns = opts["num_returns"]
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1  # the completion object (item count / error)
        task_id = TaskID.generate()
        spec = TaskSpec(
            task_id=task_id,
            kind="task",
            fn_id=fn_id,
            fn_bytes=None,
            name=opts.get("name") or self.__name__,
            args_meta=meta,
            arg_refs=arg_refs,
            num_returns=-1 if streaming else num_returns,
            return_ids=[ObjectID.generate() for _ in range(num_returns)],
            resources=build_resources(opts),
            scheduling_strategy=opts["scheduling_strategy"],
            # a replayed generator would re-register already-consumed item ids;
            # streaming tasks surface the crash instead (reference restriction
            # lifted only with generator checkpointing, which we don't do)
            max_retries=0 if streaming else opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            runtime_env=_renv(opts.get("runtime_env")),
            trace_ctx=_trace_ctx(),
        )
        refs = ctx.submit(spec)
        del pins  # safe to release: submit() pinned the args
        if streaming:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0], task_id)
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()."
        )


def _trace_ctx():
    from ray_tpu.util.tracing import get_trace_context

    return get_trace_context()
