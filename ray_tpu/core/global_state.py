"""Process-global runtime context: either the driver (with an in-process cluster) or a
worker (with a pipe to the node service).

Capability parity: reference python/ray/_private/worker.py global_worker singleton.
"""
from __future__ import annotations

import queue as _queue
import threading as _threading
from typing import Any, Optional

_worker: Optional[Any] = None  # DriverContext or WorkerContext
_cluster: Optional[Any] = None  # Cluster (driver process only)

# GC-action plumbing: __del__ finalizers (ObjectRef decref, ActorHandle kill) can
# fire during garbage collection on ANY thread — including one already holding the
# store lock or mid-pipe-send — so they must never call the runtime directly.
# SimpleQueue.put is reentrant; a daemon drains it (reference: Ray's CoreWorker
# queues ref-removals off the destructor path for the same reason).
_gc_actions: "_queue.SimpleQueue" = _queue.SimpleQueue()
_gc_drainer: Optional[_threading.Thread] = None


def enqueue_gc_action(kind: str, ident: Any) -> None:
    """Safe to call from __del__/weakref finalizers in any thread state."""
    _gc_actions.put((kind, ident))


def _drain_gc_actions() -> None:
    while True:
        kind, ident = _gc_actions.get()
        w = _worker
        if w is None:
            continue
        try:
            if kind == "decref":
                w.decref(ident)
            elif kind == "kill_actor":
                w.kill_actor(ident, no_restart=True, from_gc=True)
            elif kind == "drop_stream":
                w.drop_stream(*ident)
        # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
        except Exception:
            pass


def set_worker(w) -> None:
    global _worker, _gc_drainer
    _worker = w
    if w is not None and (_gc_drainer is None or not _gc_drainer.is_alive()):
        _gc_drainer = _threading.Thread(
            target=_drain_gc_actions, daemon=True, name="gc-action-drainer")
        _gc_drainer.start()


def worker():
    if _worker is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first"
        )
    return _worker


def try_worker():
    return _worker


def set_cluster(c) -> None:
    global _cluster
    _cluster = c


def try_cluster():
    return _cluster


def is_initialized() -> bool:
    return _worker is not None
