"""Process-global runtime context: either the driver (with an in-process cluster) or a
worker (with a pipe to the node service).

Capability parity: reference python/ray/_private/worker.py global_worker singleton.
"""
from __future__ import annotations

from typing import Any, Optional

_worker: Optional[Any] = None  # DriverContext or WorkerContext
_cluster: Optional[Any] = None  # Cluster (driver process only)


def set_worker(w) -> None:
    global _worker
    _worker = w


def worker():
    if _worker is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first"
        )
    return _worker


def try_worker():
    return _worker


def set_cluster(c) -> None:
    global _cluster
    _cluster = c


def try_cluster():
    return _cluster


def is_initialized() -> bool:
    return _worker is not None
