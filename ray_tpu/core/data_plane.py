"""Direct node-to-node bulk object transfer (the "data plane").

Capability parity: reference ObjectManager direct transfers between nodes
(src/ray/object_manager/object_manager.h:119), chunked pushes
(push_manager.h:27) and pull admission control (pull_manager.h:49). The head
process is a METADATA broker only: it tells the destination where the bytes
live and the destination pulls them straight from the source's data server in
fixed-size chunks — cross-host object bytes never transit the head, so head
NIC/RAM no longer bound object size or shuffle throughput.

Every node (the head included) runs a DataServer next to its object store and
keeps a DataClient with pooled connections per peer. Transport is the same
authkey-authenticated length-prefixed framing as the control plane
(multiprocessing.connection), but on a dedicated listener so bulk bytes never
queue behind control traffic.

Protocol (one pull per connection at a time; connections are reused):
  client -> ("pull", loc)
  server -> ("ok", total_len, is_error) | ("err", message)
  client -> ("go",)          # sent after ADMISSION: total_len bytes of budget
  server -> ceil(total_len / chunk) raw chunk frames
The admission handshake is what bounds destination memory: a node admits at
most transfer_inflight_bytes of concurrent incoming object bytes (an object
larger than the whole budget is admitted alone), matching the reference
PullManager's byte-budgeted activation of pull requests.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from multiprocessing.connection import Connection, Listener, answer_challenge, \
    deliver_challenge
from typing import Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.config import CONFIG


def _set_fd_timeouts(fd: int, seconds: float, send_only: bool = False) -> None:
    """SO_RCVTIMEO/SO_SNDTIMEO at the fd level: recv/send syscalls fail with
    EAGAIN after `seconds` of stall, so a half-dead peer cannot pin a puller
    thread (and its admission budget) forever. fd-level because
    multiprocessing.Connection bypasses Python socket timeouts."""
    s = socket.socket(fileno=os.dup(fd))
    try:
        tv = struct.pack("ll", int(seconds), int((seconds % 1) * 1_000_000))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        if not send_only:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
    finally:
        s.close()


class Admission:
    """Byte-budget + concurrency gate for in-flight pulls (pull_manager.h:49).

    FIFO: requests admit in arrival order, so a full-budget pull (a huge
    object) cannot be starved indefinitely by a stream of small pulls slicing
    the budget out from under it — matching the reference PullManager's
    in-order activation of pull requests."""

    def __init__(self, max_bytes: int, max_pulls: int):
        from collections import deque

        self.max_bytes = max(1, max_bytes)
        self._bytes = self.max_bytes
        self._pulls = max(1, max_pulls)
        self._cond = threading.Condition()
        self._queue: "deque" = deque()

    def acquire(self, n: int) -> int:
        """Block until n bytes (clamped to the whole budget) + one pull slot are
        admitted; returns the admitted byte count for the matching release()."""
        n = min(max(n, 1), self.max_bytes)
        me = object()
        with self._cond:
            self._queue.append(me)
            while self._queue[0] is not me or self._pulls <= 0 or self._bytes < n:
                self._cond.wait(timeout=1.0)
            self._queue.popleft()
            self._pulls -= 1
            self._bytes -= n
            self._cond.notify_all()  # next-in-line may also fit
        return n

    def release(self, n: int) -> None:
        with self._cond:
            self._pulls += 1
            self._bytes += n
            self._cond.notify_all()


class DataServer:
    """Serves chunked object reads from this node's local store."""

    def __init__(self, authkey: bytes,
                 read_fn: Callable[[Tuple], Tuple[bytes, bool]],
                 host: str = "0.0.0.0", port: int = 0,
                 max_streams: Optional[int] = None):
        self._read_fn = read_fn
        self._authkey = authkey
        # no authkey on the Listener: accept() would then run the auth
        # handshake INLINE, serializing all dials behind one slow/dead peer.
        # Each connection authenticates on its own thread instead, with
        # fd-level stall bounds.
        from ray_tpu.core.secure_transport import make_listener

        self._listener = make_listener((host, port), backlog=128)
        self.port: int = self._listener.address[1]
        self._shutdown = False
        # source-side cap: a broadcast to N nodes serves at most this many
        # concurrent outbound streams (push_manager.h chunked-push pacing).
        # Collective-plane servers pass a larger max_streams: their read_fn
        # blocks until the requested chunk is published, so a slot can be
        # held by a waiting reader, not just an active copy.
        self._slots = threading.Semaphore(max_streams or CONFIG.transfer_max_pulls)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="rt-data-server").start()

    def _accept_loop(self) -> None:
        errors = 0
        while not self._shutdown:
            try:
                conn = self._listener.accept()
                errors = 0
            except EOFError:
                continue  # one bad/failed dial must not stop the server
            except OSError:
                # a peer resetting mid-accept raises OSError too — only a
                # persistently-failing accept (closed listener) stops the loop
                errors += 1
                if self._shutdown or errors > 100:
                    return
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True,
                             name="rt-data-serve").start()

    def _serve_conn(self, conn: Connection) -> None:
        chunk = CONFIG.transfer_chunk_bytes
        try:
            # bounded per-connection auth + stall limits: a dead peer can pin
            # neither the accept loop nor this thread. RCVTIMEO is safe for
            # pooled idle connections because the request wait below polls
            # (select) and only recv's once bytes are ready.
            _set_fd_timeouts(conn.fileno(), CONFIG.transfer_stall_timeout_s)
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
        except BaseException:
            try:
                conn.close()
            except Exception:
                pass
            return
        try:
            while not self._shutdown:
                # idle-tolerant request wait: pooled client connections sit
                # here between pulls, so no timeout — but poll in slices so
                # shutdown is responsive
                while not conn.poll(1.0):
                    if self._shutdown:
                        return
                req = cloudpickle.loads(conn.recv_bytes())
                if req[0] != "pull":
                    conn.send_bytes(cloudpickle.dumps(("err", f"bad op {req[0]!r}")))
                    continue
                # slot held from BEFORE the object read: at most
                # transfer_max_pulls full in-memory copies exist on the source
                # at once, even when a broadcast fans out to far more peers
                # (otherwise N waiting-for-go connections = N copies = OOM)
                with self._slots:
                    try:
                        data, is_error = self._read_fn(req[1])
                    except BaseException as e:  # noqa: BLE001 — report, keep serving
                        conn.send_bytes(cloudpickle.dumps(("err", repr(e))))
                        continue
                    conn.send_bytes(cloudpickle.dumps(("ok", len(data), is_error)))
                    # the puller acquires admission between "ok" and "go", and
                    # under contention that wait is legitimate (budget pinned by
                    # other transfers) — so allow the full transfer deadline,
                    # not just the stall bound, before declaring the puller
                    # dead. This timeout is also the breaker for the theoretical
                    # cross-node slot/admission wait cycle.
                    if not conn.poll(CONFIG.transfer_timeout_s):
                        break  # puller gone (or starved past the deadline)
                    go = cloudpickle.loads(conn.recv_bytes())
                    if go[0] != "go":
                        break  # protocol desync: drop the connection
                    view = memoryview(data)
                    for off in range(0, len(data), chunk):
                        conn.send_bytes(view[off:off + chunk])
                    if not data:
                        conn.send_bytes(b"")  # zero-length objects: one empty frame
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:
            pass


class DataClient:
    """Pulls objects from peer DataServers; one pooled connection set per peer."""

    def __init__(self, authkey: bytes):
        self._authkey = authkey
        self._pool: Dict[Tuple[str, int], List[Connection]] = {}
        self._lock = threading.Lock()
        self._admission = Admission(CONFIG.transfer_inflight_bytes,
                                    CONFIG.transfer_max_pulls)

    def _dial(self, addr: Tuple[str, int]) -> Connection:
        """Connect with a bounded handshake: fd-level stall timeouts apply to
        the auth exchange AND every later recv, so a half-dead server can never
        pin a puller thread (multiprocessing's Client() would block forever)."""
        stall = CONFIG.transfer_stall_timeout_s
        from ray_tpu.core import tls_utils

        if tls_utils.use_tls():
            from ray_tpu.core.secure_transport import dial

            conn = dial(addr, timeout=min(10.0, stall))
            try:
                _set_fd_timeouts(conn.fileno(), stall)
                answer_challenge(conn, self._authkey)
                deliver_challenge(conn, self._authkey)
            except BaseException:
                conn.close()
                raise
            return conn
        s = socket.create_connection(addr, timeout=min(10.0, stall))
        s.settimeout(None)  # hand a blocking fd over; SO_*TIMEO bounds the ops
        conn = Connection(s.detach())
        try:
            _set_fd_timeouts(conn.fileno(), stall)
            answer_challenge(conn, self._authkey)
            deliver_challenge(conn, self._authkey)
        except BaseException:
            conn.close()
            raise
        return conn

    def _checkout(self, addr: Tuple[str, int]) -> Connection:
        with self._lock:
            free = self._pool.get(addr)
            if free:
                return free.pop()
        return self._dial(addr)

    def _checkin(self, addr: Tuple[str, int], conn: Connection) -> None:
        with self._lock:
            self._pool.setdefault(addr, []).append(conn)

    def pull(self, addr: Tuple[str, int], loc: Tuple,
             retry: bool = True) -> Tuple[bytes, bool]:
        """Fetch the object at loc from the peer's data server, chunked and
        admission-gated. A stale pooled connection (idle-TCP killed by NAT/
        conntrack) gets ONE retry on a fresh dial; real failures raise
        OSError/EOFError/TimeoutError (the caller decides whether to fall back
        to head relay or reconstruct). Pass retry=False when the server-side
        read is NOT idempotent (collective ring buffers count bytes read
        toward retraction — a replayed range would double-count)."""
        addr = (addr[0], int(addr[1]))
        with self._lock:
            had_pooled = bool(self._pool.get(addr))
        try:
            return self._pull_once(addr, loc)
        except (OSError, EOFError, TimeoutError):
            if not retry or not had_pooled:
                raise
            return self._pull_once(addr, loc)  # fresh dial (pool was drained)

    def _pull_once(self, addr: Tuple[str, int], loc: Tuple) -> Tuple[bytes, bool]:
        conn = self._checkout(addr)
        admitted = 0

        def recv(timeout: float) -> bytes:
            # poll-then-recv: legitimate queueing on the server (its outbound
            # slot semaphore, a busy NIC) must not trip the per-syscall stall
            # bound — only a peer that stops mid-frame should
            if not conn.poll(timeout):
                raise TimeoutError(f"data server {addr} stalled")
            return conn.recv_bytes()

        try:
            conn.send_bytes(cloudpickle.dumps(("pull", loc)))
            hdr = cloudpickle.loads(recv(CONFIG.transfer_timeout_s))
            if hdr[0] != "ok":
                raise OSError(f"data server {addr}: {hdr[1]}")
            total, is_error = int(hdr[1]), bool(hdr[2])
            admitted = self._admission.acquire(total)
            conn.send_bytes(cloudpickle.dumps(("go",)))
            buf = bytearray(total)
            got = 0
            first = True
            while got < total or total == 0:
                # first chunk may wait behind the server's slot queue; later
                # chunks stream continuously, so a long gap means a dead peer
                frame = recv(CONFIG.transfer_timeout_s if first
                             else CONFIG.transfer_stall_timeout_s)
                first = False
                if total == 0:
                    break
                buf[got:got + len(frame)] = frame
                got += len(frame)
            self._checkin(addr, conn)
            conn = None
            return bytes(buf), is_error
        finally:
            if admitted:
                self._admission.release(admitted)
            if conn is not None:  # failed mid-protocol: never reuse this conn
                try:
                    conn.close()
                except Exception:
                    pass

    def close(self) -> None:
        with self._lock:
            pools, self._pool = self._pool, {}
        for conns in pools.values():
            for c in conns:
                try:
                    c.close()
                except Exception:
                    pass


