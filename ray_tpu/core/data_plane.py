"""Direct node-to-node bulk object transfer (the "data plane").

Capability parity: reference ObjectManager direct transfers between nodes
(src/ray/object_manager/object_manager.h:119), chunked pushes
(push_manager.h:27) and pull admission control (pull_manager.h:49). The head
process is a METADATA broker only: it tells the destination where the bytes
live and the destination pulls them straight from the source's data server in
fixed-size chunks — cross-host object bytes never transit the head, so head
NIC/RAM no longer bound object size or shuffle throughput.

Every node (the head included) runs a DataServer next to its object store and
keeps a DataClient with pooled connections per peer. Transport is the same
authkey-authenticated length-prefixed framing as the control plane
(multiprocessing.connection), but on a dedicated listener so bulk bytes never
queue behind control traffic.

Copy discipline (the whole point of this module's design):
  server   read_fn may return a PinnedRead — a memoryview straight over the
           shm/arena mapping, pinned so a concurrent spill/free cannot
           invalidate it mid-transfer. Chunk frames are sent as slices of that
           view; multiprocessing's framing writes large buffers straight from
           the view (no staging copy).
  client   pull(..., into=sink) lands chunk frames with recv_bytes_into
           directly in a caller-provided buffer — typically the destination's
           own pre-created shm segment — so a pulled object is sealed in place
           with zero intermediate bytes objects.
  stripes  objects whose size the caller already knows (store location tuples
           carry it) split above CONFIG.transfer_stripe_threshold_bytes into
           up to CONFIG.transfer_stripes byte ranges pulled concurrently over
           pooled connections, using the same ("slice", loc, off, len) ranged
           reads the ring collectives use. All stripes of one pull count as
           ONE admission (one pull slot, total bytes), matching the reference
           PullManager accounting.

Protocol (one pull per connection at a time; connections are reused):
  client -> ("pull", loc)
  server -> ("ok", total_len, is_error) | ("err", message)
  client -> ("go",)          # sent after ADMISSION: total_len bytes of budget
  server -> ceil(total_len / chunk) raw chunk frames
The admission handshake is what bounds destination memory: a node admits at
most transfer_inflight_bytes of concurrent incoming object bytes (an object
larger than the whole budget is admitted alone), matching the reference
PullManager's byte-budgeted activation of pull requests.
"""
from __future__ import annotations

import functools
import os
import socket
import struct
import threading
import time
from multiprocessing.connection import Connection, Listener, answer_challenge, \
    deliver_challenge
from typing import Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.config import CONFIG
from ray_tpu.util import telemetry


def _record_pull(nbytes: int, dt_s: float, nstripes: int, path: str,
                 admission_wait_s: float) -> None:
    """Per-logical-pull load signals: byte/second counters feed the live
    GB/s figures in `ray-tpu status` / cluster_status(); the timeline event
    (only when telemetry is on) carries the per-pull shape.

    Zero-byte pulls are NOT recorded: the collective plane's bounded relay
    probes (ring.py pull_into with a short timeout) legitimately return empty
    when the range isn't published yet, and each miss would otherwise log a
    fake pull carrying ~a poll interval of 0-byte 'transfer seconds' —
    cratering the reported GB/s exactly when a rank is waiting."""
    if nbytes <= 0:
        return
    tags = {"path": path}
    telemetry.get_counter(
        "transfer_bytes_total", "object bytes pulled over the data plane",
        tag_keys=("path",)).inc(float(max(nbytes, 0)), tags=tags)
    telemetry.get_counter(
        "transfer_seconds_total", "wall seconds spent in data-plane pulls",
        tag_keys=("path",)).inc(max(dt_s, 0.0), tags=tags)
    telemetry.get_counter(
        "transfer_pulls_total", "completed data-plane pulls",
        tag_keys=("path",)).inc(1.0, tags=tags)
    if admission_wait_s > 0:
        telemetry.get_histogram(
            "transfer_admission_wait_s",
            "time pulls spent queued behind the admission byte budget").observe(
            admission_wait_s)
    if telemetry.enabled():
        telemetry.event(
            "transfer.pull", "transfer", bytes=int(nbytes), stripes=nstripes,
            path=path, gbps=round(nbytes / dt_s / 1e9, 3) if dt_s > 0 else 0.0,
            admission_wait_ms=round(admission_wait_s * 1e3, 3))


def _set_fd_timeouts(fd: int, seconds: float, send_only: bool = False) -> None:
    """SO_RCVTIMEO/SO_SNDTIMEO at the fd level: recv/send syscalls fail with
    EAGAIN after `seconds` of stall, so a half-dead peer cannot pin a puller
    thread (and its admission budget) forever. fd-level because
    multiprocessing.Connection bypasses Python socket timeouts.

    Also sets TCP_NODELAY: every chunk frame is a tiny length-prefix write
    followed by a bulk write, and Nagle holding the prefix back until the
    previous bulk segment is ACKed serializes the stream at RTT granularity —
    measured 2-4x throughput loss per stream on loopback."""
    s = socket.socket(fileno=os.dup(fd))
    try:
        tv = struct.pack("ll", int(seconds), int((seconds % 1) * 1_000_000))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        if not send_only:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transport (unix socket test listeners)
    finally:
        s.close()


@functools.lru_cache(maxsize=64)
def _is_local_host(host: str) -> bool:
    """Whether `host` names this machine — the gate for the same-host unix
    socket fast path. Cached: it sits on every dial."""
    if host in ("127.0.0.1", "localhost", "::1"):
        return True
    try:
        from ray_tpu.core.device_plane import _node_ip

        return host == _node_ip()
    # graftlint: allow[swallowed-exception] resolution failure just means "treat as remote"
    except Exception:
        return False


class PinnedRead:
    """A readable buffer a server read_fn hands the transport, pinned for the
    transfer's lifetime.

    `view` is a memoryview over the object's backing storage (shm segment,
    arena mapping, mmap'd spill file): the server streams chunk-sized slices
    of it with no staging copy. `release()` drops whatever pin keeps that
    storage valid (an arena reader pin, the view itself for shm segments —
    unlink/close defer while exported views exist) and is idempotent; the
    server calls it once streaming ends, success or not, so a concurrent
    spill_lru/free_local during a pull can never serve torn bytes."""

    __slots__ = ("view", "is_error", "_release")

    def __init__(self, view, is_error: bool = False,
                 release: Optional[Callable[[], None]] = None):
        self.view = view if isinstance(view, memoryview) else memoryview(view)
        self.is_error = bool(is_error)
        self._release = release

    @property
    def nbytes(self) -> int:
        return self.view.nbytes

    def release(self) -> None:
        rel, self._release = self._release, None
        try:
            self.view.release()
        except BufferError:
            pass  # sub-slices still in flight keep the mapping alive
        if rel is not None:
            try:
                rel()
            # graftlint: allow[swallowed-exception] pin-release callback on an already-freed mapping: nothing left to release
            except Exception:
                pass

    def __enter__(self) -> "PinnedRead":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _recv_frame_into(conn, mv: memoryview) -> int:
    """Receive one length-prefixed frame straight into `mv`, returning its
    size — the true recv-into the stdlib Connection lacks (its
    recv_bytes_into stages the whole frame in a BytesIO and then copies).
    Connection does no user-space read buffering, so reading the framing
    header off the fd and readv'ing the payload directly into the destination
    mapping is safe between its own recvs."""
    if not isinstance(conn, Connection):
        return conn.recv_bytes_into(mv)  # SecureConnection: real recv-into
    fd = conn.fileno()

    def read_exact(n: int) -> bytes:
        b = bytearray()
        while len(b) < n:
            piece = os.read(fd, n - len(b))
            if not piece:
                raise EOFError("connection closed mid-frame")
            b += piece
        return bytes(b)

    (size,) = struct.unpack("!i", read_exact(4))
    if size == -1:  # extended header (frames over 2 GiB)
        (size,) = struct.unpack("!Q", read_exact(8))
    if size > mv.nbytes:
        raise OSError(f"frame of {size} bytes exceeds buffer room ({mv.nbytes})")
    got = 0
    while got < size:
        n = os.readv(fd, [mv[got:size]])
        if n <= 0:
            raise EOFError("connection closed mid-frame")
        got += n
    return size


def _as_pinned(res) -> PinnedRead:
    """Normalize a read_fn result: PinnedRead passes through, the legacy
    (bytes, is_error) tuple gets wrapped (the bytes object itself is the pin)."""
    if isinstance(res, PinnedRead):
        return res
    data, is_error = res
    return PinnedRead(memoryview(data), is_error)


class Admission:
    """Byte-budget + concurrency gate for in-flight pulls (pull_manager.h:49).

    FIFO: requests admit in arrival order, so a full-budget pull (a huge
    object) cannot be starved indefinitely by a stream of small pulls slicing
    the budget out from under it — matching the reference PullManager's
    in-order activation of pull requests.

    Wakeups are precise: release() (and a successful acquire, which may unblock
    the next-in-line) notify the condition, so a freed budget admits the FIFO
    head immediately instead of on the next poll tick. One coarse timeout
    remains purely as a shutdown/leak guard — it never gates admission latency."""

    # shutdown guard only: a waiter re-checks at least this often even if a
    # notify was lost to an interpreter teardown; NOT an admission latency bound
    _GUARD_TIMEOUT_S = 5.0

    def __init__(self, max_bytes: int, max_pulls: int):
        from collections import deque

        self.max_bytes = max(1, max_bytes)
        self._bytes = self.max_bytes
        self.max_pulls = max(1, max_pulls)
        self._pulls = self.max_pulls
        self._cond = threading.Condition()
        self._queue: "deque" = deque()

    def acquire(self, n: int) -> int:
        """Block until n bytes (clamped to the whole budget) + one pull slot are
        admitted; returns the admitted byte count for the matching release()."""
        n = min(max(n, 1), self.max_bytes)
        me = object()
        with self._cond:
            self._queue.append(me)
            while self._queue[0] is not me or self._pulls <= 0 or self._bytes < n:
                self._cond.wait(timeout=self._GUARD_TIMEOUT_S)
            self._queue.popleft()
            self._pulls -= 1
            self._bytes -= n
            self._cond.notify_all()  # next-in-line may also fit
        return n

    def release(self, n: int) -> None:
        with self._cond:
            self._pulls += 1
            self._bytes += n
            self._cond.notify_all()

    def snapshot(self) -> Tuple[int, int]:
        """(bytes_available, pull_slots_available) — test/diagnostic seam for
        asserting the budget returned to full after failures."""
        with self._cond:
            return self._bytes, self._pulls


def _uds_name(port: int) -> str:
    """Abstract-namespace unix socket name for the data server bound to TCP
    `port` — derivable by any local client from the advertised (host, port)
    alone, no extra discovery channel."""
    return f"\0ray-tpu-dp-{port}"


class _AbstractUnixListener:
    """Linux abstract-namespace AF_UNIX listener wrapping accepts into mp
    Connections. Abstract names need no filesystem cleanup (they vanish with
    the last fd), so a SIGKILL'd server leaks nothing."""

    def __init__(self, name: str, backlog: int = 128):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(name)
        self._sock.listen(backlog)

    def accept(self) -> Connection:
        s, _ = self._sock.accept()
        return Connection(s.detach())

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class DataServer:
    """Serves chunked object reads from this node's local store.

    read_fn(loc) returns either the legacy (bytes, is_error) tuple or a
    PinnedRead whose view is streamed zero-copy (see module docstring).

    Besides the TCP listener, a plain-transport server also listens on an
    abstract AF_UNIX socket named after its TCP port: same-host pulls (P/D
    pools colocated on one machine, local object-store hits) skip the
    loopback TCP stack — measured ~1.4x bulk throughput — while the authkey
    challenge still gates every connection. TLS mode stays TCP-only."""

    def __init__(self, authkey: bytes,
                 read_fn: Callable[[Tuple], object],
                 host: str = "0.0.0.0", port: int = 0,
                 max_streams: Optional[int] = None):
        self._read_fn = read_fn
        self._authkey = authkey
        # no authkey on the Listener: accept() would then run the auth
        # handshake INLINE, serializing all dials behind one slow/dead peer.
        # Each connection authenticates on its own thread instead, with
        # fd-level stall bounds.
        from ray_tpu.core import tls_utils
        from ray_tpu.core.secure_transport import make_listener

        self._listener = make_listener((host, port), backlog=128)
        self.port: int = self._listener.address[1]
        self._shutdown = False
        # source-side cap: a broadcast to N nodes serves at most this many
        # concurrent outbound streams (push_manager.h chunked-push pacing).
        # Collective-plane servers pass a larger max_streams: their read_fn
        # blocks until the requested chunk is published, so a slot can be
        # held by a waiting reader, not just an active copy.
        self._slots = threading.Semaphore(max_streams or CONFIG.transfer_max_pulls)
        threading.Thread(target=self._accept_loop, args=(self._listener,),
                         daemon=True, name="rt-data-server").start()
        self._uds_listener = None
        if (CONFIG.transfer_uds and not tls_utils.use_tls()
                and hasattr(socket, "AF_UNIX")):
            try:
                self._uds_listener = _AbstractUnixListener(_uds_name(self.port))
            except OSError:
                pass  # abstract namespace unavailable: TCP covers everything
            else:
                threading.Thread(target=self._accept_loop,
                                 args=(self._uds_listener,), daemon=True,
                                 name="rt-data-server-uds").start()

    def _accept_loop(self, listener) -> None:
        errors = 0
        while not self._shutdown:
            try:
                conn = listener.accept()
                errors = 0
            except EOFError:
                continue  # one bad/failed dial must not stop the server
            except OSError:
                # a peer resetting mid-accept raises OSError too — only a
                # persistently-failing accept (closed listener) stops the loop
                errors += 1
                if self._shutdown or errors > 100:
                    return
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True,
                             name="rt-data-serve").start()

    def _serve_conn(self, conn: Connection) -> None:
        chunk = CONFIG.transfer_chunk_bytes
        try:
            # bounded per-connection auth + stall limits: a dead peer can pin
            # neither the accept loop nor this thread. RCVTIMEO is safe for
            # pooled idle connections because the request wait below polls
            # (select) and only recv's once bytes are ready.
            _set_fd_timeouts(conn.fileno(), CONFIG.transfer_stall_timeout_s)
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
        # graftlint: allow[swallowed-exception] best-effort close of a connection being discarded
        except BaseException:
            try:
                conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            return
        try:
            while not self._shutdown:
                # idle-tolerant request wait: pooled client connections sit
                # here between pulls, so no timeout — but poll in slices so
                # shutdown is responsive
                while not conn.poll(1.0):
                    if self._shutdown:
                        return
                req = cloudpickle.loads(conn.recv_bytes())
                if req[0] != "pull":
                    conn.send_bytes(cloudpickle.dumps(("err", f"bad op {req[0]!r}")))
                    continue
                # slot held from BEFORE the object read: at most
                # transfer_max_pulls streams exist on the source at once, even
                # when a broadcast fans out to far more peers; with pinned
                # reads a stream is a pinned mapping, not a full copy
                with self._slots:
                    try:
                        res = self._read_fn(req[1])
                    except BaseException as e:  # noqa: BLE001 — report, keep serving
                        conn.send_bytes(cloudpickle.dumps(("err", repr(e))))
                        continue
                    served_pinned = isinstance(res, PinnedRead)
                    pr = _as_pinned(res)
                    try:
                        total = pr.nbytes
                        conn.send_bytes(
                            cloudpickle.dumps(("ok", total, pr.is_error)))
                        # the puller acquires admission between "ok" and "go",
                        # and under contention that wait is legitimate (budget
                        # pinned by other transfers) — so allow the full
                        # transfer deadline, not just the stall bound, before
                        # declaring the puller dead. This timeout is also the
                        # breaker for the theoretical cross-node slot/admission
                        # wait cycle, and it bounds how long a pin can defer a
                        # spill/free of the object being served.
                        if not conn.poll(CONFIG.transfer_timeout_s):
                            break  # puller gone (or starved past the deadline)
                        go = cloudpickle.loads(conn.recv_bytes())
                        if go[0] != "go":
                            break  # protocol desync: drop the connection
                        view = pr.view
                        t_serve = time.perf_counter()
                        for off in range(0, total, chunk):
                            conn.send_bytes(view[off:off + chunk])
                        if not total:
                            conn.send_bytes(b"")  # zero-length: one empty frame
                        if total > 0:  # relay-probe misses serve empty: skip
                            path = "pinned" if served_pinned else "staged"
                            telemetry.get_counter(
                                "transfer_served_bytes_total",
                                "object bytes streamed out by this data server",
                                tag_keys=("path",)).inc(float(total),
                                                        tags={"path": path})
                            if telemetry.enabled():
                                dt = time.perf_counter() - t_serve
                                telemetry.event(
                                    "transfer.serve", "transfer", bytes=total,
                                    path=path,
                                    gbps=round(total / dt / 1e9, 3) if dt > 0
                                    else 0.0)
                    finally:
                        pr.release()
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        if self._uds_listener is not None:
            self._uds_listener.close()


def plan_stripes(size: Optional[int]) -> int:
    """How many concurrent byte-range streams a pull of `size` bytes should
    use. 1 (single-stream) when the size is unknown, below the stripe
    threshold, or striping is disabled; otherwise up to CONFIG.transfer_stripes,
    never so many that a stripe would shrink below transfer_stripe_min_bytes
    (a handshake per stripe has to buy real overlap)."""
    if size is None:
        return 1
    threshold = CONFIG.transfer_stripe_threshold_bytes
    n = CONFIG.transfer_stripes
    if threshold <= 0 or n <= 1 or size < threshold:
        return 1
    stripe_min = max(1, CONFIG.transfer_stripe_min_bytes)
    return max(1, min(n, size // stripe_min))


def stripe_ranges(total: int, n: int) -> List[Tuple[int, int]]:
    """Split [0, total) into n even (offset, length) ranges (last takes the
    remainder). Servers chunk any range length, so no alignment is needed."""
    per = -(-total // n)  # ceil
    ranges = []
    off = 0
    while off < total:
        ln = min(per, total - off)
        ranges.append((off, ln))
        off += ln
    return ranges


class DataClient:
    """Pulls objects from peer DataServers; one pooled connection set per peer.

    stats_path labels this client's pulls in the transfer metrics/events:
    "wire" for the object plane, "collective" for ring-collective planes —
    without it, chunk pulls inside one allreduce would double-count as object
    transfers in `ray-tpu status` and drown the timeline's transfer row."""

    def __init__(self, authkey: bytes, stats_path: str = "wire"):
        self._authkey = authkey
        self.stats_path = stats_path
        self._pool: Dict[Tuple[str, int], List[Connection]] = {}
        self._lock = threading.Lock()
        self._admission = Admission(CONFIG.transfer_inflight_bytes,
                                    CONFIG.transfer_max_pulls)

    def _dial(self, addr: Tuple[str, int]) -> Connection:
        """Connect with a bounded handshake: fd-level stall timeouts apply to
        the auth exchange AND every later recv, so a half-dead server can never
        pin a puller thread (multiprocessing's Client() would block forever)."""
        stall = CONFIG.transfer_stall_timeout_s
        from ray_tpu.core import tls_utils

        if tls_utils.use_tls():
            from ray_tpu.core.secure_transport import dial

            conn = dial(addr, timeout=min(10.0, stall))
            try:
                _set_fd_timeouts(conn.fileno(), stall)
                answer_challenge(conn, self._authkey)
                deliver_challenge(conn, self._authkey)
            except BaseException:
                conn.close()
                raise
            return conn
        s = self._dial_socket(addr, min(10.0, stall))
        s.settimeout(None)  # hand a blocking fd over; SO_*TIMEO bounds the ops
        conn = Connection(s.detach())
        try:
            _set_fd_timeouts(conn.fileno(), stall)
            answer_challenge(conn, self._authkey)
            deliver_challenge(conn, self._authkey)
        except BaseException:
            conn.close()
            raise
        return conn

    @staticmethod
    def _dial_socket(addr: Tuple[str, int], timeout: float) -> socket.socket:
        """A connected stream socket to the peer data server: the abstract
        unix socket when the peer is this host (skips the loopback TCP stack,
        ~1.4x bulk throughput), TCP otherwise — or when the unix dial fails
        (older server, non-Linux), so the fast path degrades silently."""
        if (CONFIG.transfer_uds and hasattr(socket, "AF_UNIX")
                and _is_local_host(addr[0])):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.settimeout(timeout)
                s.connect(_uds_name(int(addr[1])))
                return s
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
        return socket.create_connection(addr, timeout=timeout)

    def _checkout(self, addr: Tuple[str, int]) -> Tuple[Connection, bool]:
        """Returns (conn, from_pool). from_pool is recorded HERE, not sampled
        by the caller beforehand: a concurrent puller can drain (or refill) the
        pool between a peek and the checkout, and the stale-connection retry
        must key on what this pull actually used."""
        with self._lock:
            free = self._pool.get(addr)
            if free:
                return free.pop(), True
        return self._dial(addr), False

    def _checkin(self, addr: Tuple[str, int], conn: Connection) -> None:
        with self._lock:
            self._pool.setdefault(addr, []).append(conn)

    def pull(self, addr: Tuple[str, int], loc: Tuple, retry: bool = True,
             into: Optional[Callable[[int, bool], memoryview]] = None,
             size_hint: Optional[int] = None) -> Tuple[Optional[bytes], bool]:
        """Fetch the object at loc from the peer's data server, chunked and
        admission-gated.

        into: optional sink factory. Called once per attempt as
        into(total_len, is_error) -> writable memoryview of exactly total_len
        bytes; chunk frames land directly in it (recv_bytes_into — no
        intermediate bytes) and pull returns (None, is_error). It may be called
        again on a retry (same arguments) and must then return a buffer that is
        safe to overwrite from offset 0.

        size_hint: the object's frame size when the caller already knows it
        (store location tuples carry it). Sizes at or above
        CONFIG.transfer_stripe_threshold_bytes split into plan_stripes()
        concurrent byte-range pulls — ("slice", loc, off, len) ranged reads —
        that together count as ONE admission. Only pass it for locations the
        server reads idempotently through a slice-aware read_fn
        (object_store.read_pinned_any / read_raw_any).

        A stale pooled connection (idle-TCP killed by NAT/conntrack) gets ONE
        retry on a fresh dial; real failures raise OSError/EOFError/
        TimeoutError (the caller decides whether to fall back to head relay or
        reconstruct). Pass retry=False when the server-side read is NOT
        idempotent (collective ring buffers count bytes read toward
        retraction — a replayed range would double-count)."""
        from ray_tpu.util import fault_injection

        fault_injection.fail_point("data_plane.pull", addr=addr,
                                   size_hint=size_hint)
        addr = (addr[0], int(addr[1]))
        nstripes = plan_stripes(size_hint)
        if nstripes > 1:
            return self._pull_striped(addr, loc, int(size_hint), nstripes,
                                      into, retry)
        return self._pull_guarded(addr, loc, retry, into=into)

    def _pull_guarded(self, addr, loc, retry, into=None, admitted_by_caller=False):
        """One logical pull with the stale-pool retry: retries exactly when the
        failing attempt ran on a pooled (possibly NAT-reaped) connection."""
        try:
            return self._pull_once(addr, loc, into=into,
                                   admitted_by_caller=admitted_by_caller)
        except _StalePooledConnection as e:
            if not retry:
                raise e.cause
            return self._pull_once(addr, loc, into=into,
                                   admitted_by_caller=admitted_by_caller,
                                   fresh=True)

    def _pull_once(self, addr: Tuple[str, int], loc: Tuple,
                   into=None, admitted_by_caller=False,
                   fresh: bool = False) -> Tuple[Optional[bytes], bool]:
        t_start = time.perf_counter()
        admission_wait = 0.0
        if fresh:
            conn, from_pool = self._dial(addr), False
        else:
            conn, from_pool = self._checkout(addr)
        admitted = 0

        def recv(timeout: float) -> bytes:
            # poll-then-recv: legitimate queueing on the server (its outbound
            # slot semaphore, a busy NIC) must not trip the per-syscall stall
            # bound — only a peer that stops mid-frame should
            if not conn.poll(timeout):
                raise TimeoutError(f"data server {addr} stalled")
            return conn.recv_bytes()

        try:
            conn.send_bytes(cloudpickle.dumps(("pull", loc)))
            hdr = cloudpickle.loads(recv(CONFIG.transfer_timeout_s))
            if hdr[0] != "ok":
                raise OSError(f"data server {addr}: {hdr[1]}")
            total, is_error = int(hdr[1]), bool(hdr[2])
            if not admitted_by_caller:
                t_adm = time.perf_counter()
                admitted = self._admission.acquire(total)
                admission_wait = time.perf_counter() - t_adm
            conn.send_bytes(cloudpickle.dumps(("go",)))
            # destination buffer: sink factory (recv straight into the final
            # shm mapping / a stripe's window of it), or a plain bytearray for
            # the legacy bytes return
            out = None
            if into is not None:
                try:
                    mv = into(total, is_error)
                except (OSError, EOFError, TimeoutError) as e:
                    # deterministic local failure (e.g. a stripe range
                    # mismatch from a stale recorded size), NOT a transport
                    # error: a fresh-dial retry would fail identically
                    e._rt_local_error = True
                    raise
            else:
                out = bytearray(total)
                mv = memoryview(out)
            if mv.nbytes < total:
                e = OSError(f"pull sink too small: {mv.nbytes} < {total} bytes")
                e._rt_local_error = True
                raise e
            got = 0
            first = True
            while got < total or total == 0:
                # first chunk may wait behind the server's slot queue; later
                # chunks stream continuously, so a long gap means a dead peer
                if not conn.poll(CONFIG.transfer_timeout_s if first
                                 else CONFIG.transfer_stall_timeout_s):
                    raise TimeoutError(f"data server {addr} stalled")
                first = False
                if total == 0:
                    conn.recv_bytes()
                    break
                got += _recv_frame_into(conn, mv[got:])
            self._checkin(addr, conn)
            conn = None
            if not admitted_by_caller:
                # stripe sub-pulls are accounted once by _pull_striped
                _record_pull(total, time.perf_counter() - t_start, 1,
                             self.stats_path, admission_wait)
            return (bytes(out) if out is not None else None), is_error
        except (OSError, EOFError, TimeoutError) as e:
            if from_pool and not getattr(e, "_rt_local_error", False):
                # nothing landed yet that a fresh attempt can't redo: surface
                # the provenance so _pull_guarded retries exactly once
                raise _StalePooledConnection(e) from e
            raise
        finally:
            if admitted:
                self._admission.release(admitted)
            if conn is not None:  # failed mid-protocol: never reuse this conn
                try:
                    conn.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass

    def _pull_striped(self, addr, loc, total, nstripes, into, retry):
        """Pull [0, total) as nstripes concurrent ranged sub-pulls. One
        admission covers all stripes; any stripe failure aborts the pull (each
        stripe still gets the single stale-pool retry — ranged store reads are
        idempotent). The sink (or fallback bytearray) is shared: stripes write
        disjoint ranges, so no ordering between them matters."""
        ranges = stripe_ranges(total, nstripes)
        t_start = time.perf_counter()
        admitted = self._admission.acquire(total)
        admission_wait = time.perf_counter() - t_start
        out: Optional[bytearray] = None
        sink_holder: Dict[str, memoryview] = {}
        sink_lock = threading.Lock()
        errors: List[BaseException] = []
        is_error_box: List[bool] = [False]

        def stripe_sink(range_off: int, range_len: int):
            def make(rlen: int, is_err: bool):
                # first header wins: allocate the full-object sink once, every
                # stripe then writes its own disjoint window of it
                with sink_lock:
                    if "mv" not in sink_holder:
                        if into is not None:
                            sink_holder["mv"] = into(total, is_err)
                        else:
                            nonlocal out
                            out = bytearray(total)
                            sink_holder["mv"] = memoryview(out)
                        is_error_box[0] = is_err
                if rlen != range_len:
                    raise OSError(
                        f"striped pull range mismatch at +{range_off}: "
                        f"server has {rlen}, expected {range_len}")
                return sink_holder["mv"][range_off:range_off + range_len]
            return make

        def run(off: int, ln: int) -> None:
            try:
                self._pull_guarded(addr, ("slice", loc, off, ln), retry,
                                   into=stripe_sink(off, ln),
                                   admitted_by_caller=True)
            except BaseException as e:  # noqa: BLE001 — joined + re-raised below
                errors.append(e)

        try:
            threads = [threading.Thread(target=run, args=r, daemon=True,
                                        name="rt-stripe") for r in ranges[1:]]
            for t in threads:
                t.start()
            run(ranges[0][0], ranges[0][1])
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
            _record_pull(total, time.perf_counter() - t_start, nstripes,
                         self.stats_path, admission_wait)
            return (bytes(out) if out is not None else None), is_error_box[0]
        finally:
            self._admission.release(admitted)

    def close(self) -> None:
        with self._lock:
            pools, self._pool = self._pool, {}
        for conns in pools.values():
            for c in conns:
                try:
                    c.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass


class _StalePooledConnection(Exception):
    """Internal marker: a pull attempt failed on a connection that came out of
    the pool (so the failure may just be idle-TCP reaped by NAT/conntrack).
    Carries the real transport error for callers that opt out of the retry."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause
