"""Placement groups: atomic gang reservation of resource bundles across nodes.

Capability parity: reference python/ray/util/placement_group.py (PlacementGroup:42,
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) and the GCS 2-phase-commit scheduler
(gcs_placement_group_scheduler.h PrepareResources:381 / CommitBundleResources:458).
In-process deployment does prepare (try_acquire on every bundle, with rollback on any
failure) then commit (record bundle sub-ledgers) under one scheduler pass — the same
all-or-nothing semantics without the cross-daemon RPC.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .exceptions import PlacementGroupError
from .ids import NodeID, PlacementGroupID
from .resources import ResourceLedger


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]
    node_id: Optional[NodeID] = None
    ledger: Optional[ResourceLedger] = None  # tracks use *within* the reservation


class PlacementGroup:
    """User handle. Compare reference PlacementGroup (placement_group.py:42)."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]], strategy: str, name: str = ""):
        self.id = pg_id
        self._bundle_specs = bundles
        self._strategy = strategy
        self._name = name
        self._ready_event = threading.Event()
        self._failed: Optional[str] = None

    def _maybe_hydrate(self) -> None:
        """Deferred worker-side hydration (set up by _restore_pg): fetch
        bundle_specs/strategy/name from the node service on first use — NEVER
        during unpickle (recv-thread deadlock, see _restore_pg). Transient poll
        failures keep the flag set so a later access retries."""
        if not getattr(self, "_needs_hydration", False):
            return
        data = self._remote_poll(self.id)
        if data is not None:
            self._needs_hydration = False
            self._bundle_specs, self._strategy, self._name = data[0], data[1], data[2]
            if data[3] and not data[4]:
                self._ready_event.set()

    # hydrating attribute views: plain reads on a worker-side replica handle must
    # see real values, not placeholder defaults
    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        self._maybe_hydrate()
        return self._bundle_specs

    @bundle_specs.setter
    def bundle_specs(self, v):
        self._bundle_specs = v

    @property
    def strategy(self) -> str:
        self._maybe_hydrate()
        return self._strategy

    @strategy.setter
    def strategy(self, v):
        self._strategy = v

    @property
    def name(self) -> str:
        self._maybe_hydrate()
        return self._name

    @name.setter
    def name(self, v):
        self._name = v

    def ready(self):
        """Returns an ObjectRef resolving when the group is placed (reference API shape)."""
        from . import global_state

        self._maybe_hydrate()
        return global_state.worker().pg_ready_ref(self)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        self._maybe_hydrate()
        poll = getattr(self, "_remote_poll", None)
        if poll is not None:
            # Worker-side replica handle: poll the node service.
            import time as _time

            deadline = None if timeout_seconds is None else _time.monotonic() + timeout_seconds
            while True:
                data = poll(self.id)
                if data is not None and data[3]:  # is_ready
                    if data[4]:
                        raise PlacementGroupError(data[4])
                    self._ready_event.set()
                    return True
                if deadline is not None and _time.monotonic() >= deadline:
                    return False
                _time.sleep(0.02)
        ok = self._ready_event.wait(timeout_seconds)
        if ok and self._failed:
            raise PlacementGroupError(self._failed)
        return ok

    @property
    def is_ready(self) -> bool:
        self._maybe_hydrate()
        poll = getattr(self, "_remote_poll", None)
        if poll is not None:
            data = poll(self.id)
            return bool(data is not None and data[3] and not data[4])
        return self._ready_event.is_set() and not self._failed

    def __reduce__(self):
        # Serialized handles carry only the id; receivers look up the live group.
        return (_restore_pg, (self.id,))


def _restore_pg(pg_id):
    from . import global_state

    cluster = global_state.try_cluster()
    if cluster is not None:
        live = cluster.pg_manager.lookup(pg_id)
        if live is not None:
            return live
        with cluster._lock:
            for p in cluster.pending_pgs:
                if p.id == pg_id:
                    return p
    pg = PlacementGroup.__new__(PlacementGroup)
    pg.id = pg_id
    pg._bundle_specs = []
    pg._strategy = "PACK"
    pg._name = ""
    pg._ready_event = threading.Event()
    pg._failed = None
    w = global_state.try_worker()
    if w is not None and cluster is None:
        # Worker process. CRITICAL: no runtime calls here — unpickling happens on
        # the worker's recv/demux thread, and a _request() from that thread
        # deadlocks (it is the only thread that can deliver the reply). Hydration
        # from the node service is deferred to first use (_maybe_hydrate).
        pg._remote_poll = lambda pid: w.lookup_placement_group(pid)
        pg._needs_hydration = True
    return pg


VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroupManager:
    """Places bundles on nodes atomically; owns committed reservations."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[PlacementGroupID, Tuple[PlacementGroup, List[Bundle]]] = {}

    def lookup(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            entry = self._groups.get(pg_id)
            return entry[0] if entry else None

    def bundles(self, pg_id: PlacementGroupID) -> List[Bundle]:
        with self._lock:
            entry = self._groups.get(pg_id)
            return entry[1] if entry else []

    def try_place(
        self,
        pg: PlacementGroup,
        nodes: List[Tuple[NodeID, ResourceLedger]],
    ) -> bool:
        """Prepare+commit: reserve every bundle or nothing. Returns False if infeasible now."""
        strategy = pg.strategy
        placement = self._plan(pg.bundle_specs, strategy, nodes)
        if placement is None:
            return False
        # Prepare: acquire all, rollback on any failure (concurrent acquirers may race us).
        acquired: List[Tuple[ResourceLedger, Dict[str, float]]] = []
        ok = True
        for (node_id, ledger), spec in zip(placement, pg.bundle_specs):
            if ledger.try_acquire(spec):
                acquired.append((ledger, spec))
            else:
                ok = False
                break
        if not ok:
            for ledger, spec in acquired:
                ledger.release(spec)
            return False
        # Commit.
        bundles = []
        for i, ((node_id, _ledger), spec) in enumerate(zip(placement, pg.bundle_specs)):
            bundles.append(
                Bundle(index=i, resources=spec, node_id=node_id, ledger=ResourceLedger(spec))
            )
        with self._lock:
            self._groups[pg.id] = (pg, bundles)
        pg._ready_event.set()
        return True

    def _plan(
        self,
        specs: List[Dict[str, float]],
        strategy: str,
        nodes: List[Tuple[NodeID, ResourceLedger]],
    ) -> Optional[List[Tuple[NodeID, ResourceLedger]]]:
        """Choose a node per bundle honoring the strategy, against current availability."""
        if not nodes:
            return None
        # Work against a snapshot of availability so multi-bundle fits are planned coherently.
        avail = {nid: dict(ledger.available()) for nid, ledger in nodes}

        def fits(nid, spec):
            a = avail[nid]
            return all(a.get(k, 0.0) + 1e-9 >= v for k, v in spec.items() if v > 1e-9)

        def take(nid, spec):
            a = avail[nid]
            for k, v in spec.items():
                if v > 1e-9:
                    a[k] = a.get(k, 0.0) - v

        by_id = dict(nodes)
        plan: List[Tuple[NodeID, ResourceLedger]] = []

        if strategy in ("PACK", "STRICT_PACK"):
            # Try to land everything on one node first.
            for nid, ledger in nodes:
                snapshot = dict(avail[nid])
                if all(self._fits_seq(snapshot, specs)):
                    return [(nid, ledger)] * len(specs)
            if strategy == "STRICT_PACK":
                return None
            # PACK falls back to best-effort greedy.
            for spec in specs:
                placed = False
                for nid, ledger in nodes:
                    if fits(nid, spec):
                        take(nid, spec)
                        plan.append((nid, ledger))
                        placed = True
                        break
                if not placed:
                    return None
            return plan

        if strategy in ("SPREAD", "STRICT_SPREAD"):
            used_nodes = set()
            for spec in specs:
                placed = False
                # Prefer nodes not already used by this group.
                ordered = sorted(nodes, key=lambda nl: (nl[0] in used_nodes,))
                for nid, ledger in ordered:
                    if strategy == "STRICT_SPREAD" and nid in used_nodes:
                        continue
                    if fits(nid, spec):
                        take(nid, spec)
                        used_nodes.add(nid)
                        plan.append((nid, ledger))
                        placed = True
                        break
                if not placed:
                    return None
            return plan

        raise PlacementGroupError(f"unknown strategy {strategy!r}")

    @staticmethod
    def _fits_seq(avail: Dict[str, float], specs: List[Dict[str, float]]):
        for spec in specs:
            ok = all(avail.get(k, 0.0) + 1e-9 >= v for k, v in spec.items() if v > 1e-9)
            yield ok
            if not ok:
                return
            for k, v in spec.items():
                if v > 1e-9:
                    avail[k] = avail.get(k, 0.0) - v

    def remove(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            entry = self._groups.pop(pg_id, None)
        if entry is None:
            return
        _pg, bundles = entry
        # Return reserved capacity to the owning node ledgers.
        from . import global_state

        cluster = global_state.try_cluster()
        if cluster is None:
            return
        for b in bundles:
            node = cluster.get_node_runtime(b.node_id)
            if node is not None:
                node.ledger.release(b.resources)
