"""User-facing exception types.

Capability parity: reference python/ray/exceptions.py (RayTaskError, RayActorError,
GetTimeoutError, ObjectLostError, WorkerCrashedError, ...).
"""
from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task; re-raised at ray_tpu.get()."""

    def __init__(self, cause: BaseException, task_desc: str = "", tb_str: str = ""):
        self.cause = cause
        self.task_desc = task_desc
        if tb_str:
            self.tb_str = tb_str
        elif isinstance(cause, BaseException):
            self.tb_str = "".join(
                traceback.format_exception(type(cause), cause, cause.__traceback__)
            )
        else:
            self.tb_str = ""
        super().__init__(f"task {task_desc} failed: {cause!r}\n{self.tb_str}")

    def __reduce__(self):
        return (TaskError, (self.cause, self.task_desc, self.tb_str))


class ActorError(RayTpuError):
    """The actor died (process exit, creation failure, or kill) before/while executing."""


class ActorDiedError(ActorError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class OutOfMemoryError(WorkerCrashedError):
    """Worker killed by the memory monitor (reference ray.exceptions.OutOfMemoryError
    raised by MemoryMonitor-driven worker killing, src/ray/common/memory_monitor.h:52)."""


class CollectiveAbortError(RayTpuError):
    """A collective group was poisoned: a member rank died mid-op, the group was
    re-initialized under the caller (stale epoch), or an operator aborted it.
    Raised instead of letting survivors burn the full collective_op_timeout_s.

    Carries enough context to act on without parsing the message: the group
    name, the group epoch the caller was participating in, the rank whose
    death triggered the abort (None for operator/epoch aborts), and the
    originating cause when one exists (e.g. the WorkerCrashedError from core
    worker-death cleanup, or a peer socket error re-labeled by the abort
    verdict)."""

    def __init__(self, group_name: str, reason: str, failed_rank=None,
                 epoch=None, cause=None):
        self.group_name = group_name
        self.reason = reason
        self.failed_rank = failed_rank
        self.epoch = epoch
        self.cause = cause
        msg = f"collective group {group_name!r} aborted (epoch {epoch}"
        if failed_rank is not None:
            msg += f", failed rank {failed_rank}"
        msg += f"): {reason}"
        super().__init__(msg)

    def __reduce__(self):
        # exceptions cross process boundaries wrapped in TaskError; keep the
        # typed fields through the pickle round trip
        return (CollectiveAbortError,
                (self.group_name, self.reason, self.failed_rank, self.epoch,
                 self.cause))


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
