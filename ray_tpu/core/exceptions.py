"""User-facing exception types.

Capability parity: reference python/ray/exceptions.py (RayTaskError, RayActorError,
GetTimeoutError, ObjectLostError, WorkerCrashedError, ...).
"""
from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task; re-raised at ray_tpu.get()."""

    def __init__(self, cause: BaseException, task_desc: str = "", tb_str: str = ""):
        self.cause = cause
        self.task_desc = task_desc
        if tb_str:
            self.tb_str = tb_str
        elif isinstance(cause, BaseException):
            self.tb_str = "".join(
                traceback.format_exception(type(cause), cause, cause.__traceback__)
            )
        else:
            self.tb_str = ""
        super().__init__(f"task {task_desc} failed: {cause!r}\n{self.tb_str}")

    def __reduce__(self):
        return (TaskError, (self.cause, self.task_desc, self.tb_str))


class ActorError(RayTpuError):
    """The actor died (process exit, creation failure, or kill) before/while executing."""


class ActorDiedError(ActorError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class OutOfMemoryError(WorkerCrashedError):
    """Worker killed by the memory monitor (reference ray.exceptions.OutOfMemoryError
    raised by MemoryMonitor-driven worker killing, src/ray/common/memory_monitor.h:52)."""


class CollectiveAbortError(RayTpuError):
    """A collective group was poisoned: a member rank died mid-op, the group was
    re-initialized under the caller (stale epoch), or an operator aborted it.
    Raised instead of letting survivors burn the full collective_op_timeout_s.

    Carries enough context to act on without parsing the message: the group
    name, the group epoch the caller was participating in, the rank whose
    death triggered the abort (None for operator/epoch aborts), and the
    originating cause when one exists (e.g. the WorkerCrashedError from core
    worker-death cleanup, or a peer socket error re-labeled by the abort
    verdict)."""

    def __init__(self, group_name: str, reason: str, failed_rank=None,
                 epoch=None, cause=None):
        self.group_name = group_name
        self.reason = reason
        self.failed_rank = failed_rank
        self.epoch = epoch
        self.cause = cause
        msg = f"collective group {group_name!r} aborted (epoch {epoch}"
        if failed_rank is not None:
            msg += f", failed rank {failed_rank}"
        msg += f"): {reason}"
        super().__init__(msg)

    def __reduce__(self):
        # exceptions cross process boundaries wrapped in TaskError; keep the
        # typed fields through the pickle round trip
        return (CollectiveAbortError,
                (self.group_name, self.reason, self.failed_rank, self.epoch,
                 self.cause))


class ReplicaUnavailableError(RayTpuError):
    """A serve replica could not take (or finish) a request: its actor died,
    its worker crashed, or it is draining ahead of a scale-down. The handle's
    retry plane classifies these as safe to resend to a DIFFERENT replica
    (for deployments with retryable=True); user-code exceptions never are.

    Typed fields survive the cross-process pickle round trip (the
    CollectiveAbortError convention) so callers can act without parsing."""

    def __init__(self, app_name: str, deployment_name: str, replica: str = "",
                 reason: str = "", cause=None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica = replica
        self.cause = cause
        self.reason = reason
        msg = f"replica unavailable for {app_name}/{deployment_name}"
        if replica:
            msg += f" (replica {replica})"
        if reason:
            msg += f": {reason}"
        super().__init__(msg)

    def __reduce__(self):
        return (ReplicaUnavailableError,
                (self.app_name, self.deployment_name, self.replica,
                 self.reason, self.cause))


class HeadUnavailableError(RayTpuError):
    """The head (GCS/control plane) is unreachable and bounded reconnection
    gave up — raised from head-requiring operations (ray_tpu.get/wait
    resolution, new actor creation, named-actor lookup) instead of raw socket
    errors or indefinite hangs. Degraded-mode paths (routers pinning their
    last long-poll view, worker-to-worker data pulls) do NOT raise this; only
    operations that genuinely need the head do.

    Carries the outage age so callers (the serve retry plane, the chaos
    bench) can decide whether to keep waiting for a head restart or surface
    the failure. Typed fields survive the cross-process pickle round trip
    (the CollectiveAbortError convention)."""

    def __init__(self, outage_started_at: float = 0.0, attempts: int = 0,
                 reason: str = "", cause=None):
        self.outage_started_at = outage_started_at  # time.time() at first loss
        self.attempts = attempts  # reconnect attempts made before giving up
        self.reason = reason
        self.cause = cause
        import time as _time

        age = max(0.0, _time.time() - outage_started_at) if outage_started_at else 0.0
        msg = (f"head unavailable for {age:.1f}s "
               f"after {attempts} reconnect attempt(s)")
        if reason:
            msg += f": {reason}"
        super().__init__(msg)

    @property
    def outage_age_s(self) -> float:
        import time as _time

        if not self.outage_started_at:
            return 0.0
        return max(0.0, _time.time() - self.outage_started_at)

    def __reduce__(self):
        return (HeadUnavailableError,
                (self.outage_started_at, self.attempts, self.reason,
                 self.cause))


class BackPressureError(RayTpuError):
    """Load shed: the deployment's queue limit (max_ongoing_requests x replicas
    + max_queued_requests) is exceeded, so the request is rejected FAST instead
    of queueing into latency collapse. `retry_after_s` is the caller's hint for
    when capacity is likely to free (the proxies surface it as a Retry-After
    header on a 503 / RESOURCE_EXHAUSTED)."""

    def __init__(self, app_name: str, deployment_name: str, queue_depth: int = 0,
                 limit: int = 0, retry_after_s: float = 1.0):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.queue_depth = queue_depth
        self.limit = limit
        self.retry_after_s = retry_after_s
        super().__init__(
            f"request to {app_name}/{deployment_name} shed: {queue_depth} "
            f"in flight >= limit {limit} (retry after {retry_after_s:.1f}s)")

    def __reduce__(self):
        return (BackPressureError,
                (self.app_name, self.deployment_name, self.queue_depth,
                 self.limit, self.retry_after_s))


class FaultInjectedError(RayTpuError):
    """Raised by an armed `util/fault_injection.py` fail point in "error" mode.

    Chaos tooling's stand-in for infrastructure failure (NOT a user-code
    error): the serve retry plane treats it like a replica death so injection
    drives the same recovery paths a real crash would."""

    def __init__(self, site: str, context=None):
        self.site = site
        self.context = dict(context or {})
        super().__init__(f"fault injected at {site!r}"
                         + (f" ({self.context})" if self.context else ""))

    def __reduce__(self):
        return (FaultInjectedError, (self.site, self.context))


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
