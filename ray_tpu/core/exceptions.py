"""User-facing exception types.

Capability parity: reference python/ray/exceptions.py (RayTaskError, RayActorError,
GetTimeoutError, ObjectLostError, WorkerCrashedError, ...).
"""
from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task; re-raised at ray_tpu.get()."""

    def __init__(self, cause: BaseException, task_desc: str = "", tb_str: str = ""):
        self.cause = cause
        self.task_desc = task_desc
        if tb_str:
            self.tb_str = tb_str
        elif isinstance(cause, BaseException):
            self.tb_str = "".join(
                traceback.format_exception(type(cause), cause, cause.__traceback__)
            )
        else:
            self.tb_str = ""
        super().__init__(f"task {task_desc} failed: {cause!r}\n{self.tb_str}")

    def __reduce__(self):
        return (TaskError, (self.cause, self.task_desc, self.tb_str))


class ActorError(RayTpuError):
    """The actor died (process exit, creation failure, or kill) before/while executing."""


class ActorDiedError(ActorError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class OutOfMemoryError(WorkerCrashedError):
    """Worker killed by the memory monitor (reference ray.exceptions.OutOfMemoryError
    raised by MemoryMonitor-driven worker killing, src/ray/common/memory_monitor.h:52)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
