"""Per-node resource ledger with atomic acquire/release.

Capability parity: reference LocalResourceManager / ClusterResourceManager
(src/ray/raylet/scheduling/). Resources are float-valued named capacities
(CPU, TPU, memory, custom); TPU pod-slice head resources ("TPU-v5e-8-head")
follow the reference's accelerator-manager convention (python/ray/_private/
accelerators/tpu.py:376).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

EPS = 1e-9


class ResourceLedger:
    def __init__(self, total: Dict[str, float]):
        self._lock = threading.Lock()
        self.total = dict(total)
        self._available = dict(total)

    def available(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._available)

    def can_fit(self, request: Dict[str, float]) -> bool:
        with self._lock:
            return self._can_fit_locked(request)

    def _can_fit_locked(self, request: Dict[str, float]) -> bool:
        for k, v in request.items():
            if v <= EPS:
                continue
            if self._available.get(k, 0.0) + EPS < v:
                return False
        return True

    def feasible(self, request: Dict[str, float]) -> bool:
        """Could this request EVER fit on this node (against total, not available)?"""
        with self._lock:
            for k, v in request.items():
                if v <= EPS:
                    continue
                if self.total.get(k, 0.0) + EPS < v:
                    return False
            return True

    def try_acquire(self, request: Dict[str, float]) -> bool:
        with self._lock:
            if not self._can_fit_locked(request):
                return False
            for k, v in request.items():
                if v > EPS:
                    self._available[k] = self._available.get(k, 0.0) - v
            return True

    def release(self, request: Dict[str, float]) -> None:
        with self._lock:
            for k, v in request.items():
                if v > EPS:
                    self._available[k] = min(
                        self.total.get(k, 0.0), self._available.get(k, 0.0) + v
                    )

    def force_acquire(self, request: Dict[str, float]) -> None:
        """Acquire allowing temporary oversubscription (worker resuming from a block)."""
        with self._lock:
            for k, v in request.items():
                if v > EPS:
                    self._available[k] = self._available.get(k, 0.0) - v

    def add_capacity(self, extra: Dict[str, float]) -> None:
        with self._lock:
            for k, v in extra.items():
                self.total[k] = self.total.get(k, 0.0) + v
                self._available[k] = self._available.get(k, 0.0) + v

    def remove_capacity(self, sub: Dict[str, float]) -> None:
        with self._lock:
            for k, v in sub.items():
                self.total[k] = max(0.0, self.total.get(k, 0.0) - v)
                self._available[k] = self._available.get(k, 0.0) - v

    def utilization(self) -> float:
        with self._lock:
            used = 0.0
            cap = 0.0
            for k, t in self.total.items():
                if t <= EPS:
                    continue
                used += t - self._available.get(k, 0.0)
                cap += t
            return used / cap if cap > EPS else 0.0


def normalize_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if num_cpus is not None:
        out["CPU"] = float(num_cpus)
    if num_tpus is not None:
        out["TPU"] = float(num_tpus)
    if memory is not None:
        out["memory"] = float(memory)
    if resources:
        for k, v in resources.items():
            if k in ("CPU", "TPU", "memory") and k in out:
                raise ValueError(f"duplicate resource {k}")
            out[k] = float(v)
    return out
