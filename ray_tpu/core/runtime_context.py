"""Runtime context introspection (reference: python/ray/runtime_context.py)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import global_state


@dataclass
class RuntimeContext:
    node_id: str
    worker_id: str
    task_id: Optional[str]
    actor_id: Optional[str]
    accel: str

    def get_node_id(self) -> str:
        return self.node_id

    def get_task_id(self) -> Optional[str]:
        return self.task_id

    def get_actor_id(self) -> Optional[str]:
        return self.actor_id

    def get_worker_id(self) -> str:
        return self.worker_id


def get_runtime_context() -> RuntimeContext:
    info = global_state.worker().runtime_context()
    return RuntimeContext(**info)
