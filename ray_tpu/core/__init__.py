from . import global_state  # noqa: F401
