"""ObjectRef: a future for a value in the object store.

Capability parity: reference ObjectRef (python/ray/_raylet.pyx) + distributed refcounting
(src/ray/core_worker/reference_count.cc). Ownership model: the driver node coordinator owns
the directory; driver-side refs participate in refcounting via their Python lifetime
(__del__ -> decref). Worker-side refs are borrowed and do not decref (the owner's ref
pins the object for the duration of the borrow).
"""
from __future__ import annotations

from typing import Optional, Tuple

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "_owned", "__weakref__")

    def __init__(self, oid: ObjectID, owned: bool = False):
        self.id = oid
        self._owned = owned

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def future(self):
        """Return a concurrent.futures.Future resolving to the object's value."""
        from . import global_state

        return global_state.worker().as_future(self)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __reduce__(self):
        # Refs are serialized as borrows; ownership never transfers through pickling.
        return (ObjectRef, (self.id,))

    def __del__(self):
        if self._owned:
            from . import global_state

            try:
                # NEVER call the runtime here: __del__ can run via GC on a thread
                # that already holds the store lock or is mid-pipe-send — the
                # decref is queued and applied by the gc-action drainer
                if global_state.try_worker() is not None:
                    global_state.enqueue_gc_action("decref", self.id)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def stream_item_id(task_id, index: int) -> ObjectID:
    """Deterministic ObjectID of a streaming task's index-th yielded item.

    Derived from the task id so producer and consumer agree without a round
    trip (reference: dynamically-created return ids of streaming generators,
    python/ray/_raylet.pyx:1138)."""
    import hashlib

    digest = hashlib.sha256(task_id.binary() + b"stream" +
                            index.to_bytes(8, "little")).digest()
    return ObjectID(digest[: ObjectID.SIZE])


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded items (num_returns="streaming").

    Yields ObjectRefs as the producer materializes them; the completion object
    (the task's ordinary return) carries the final item count — or the error,
    which this generator re-raises at the failure point. Reference:
    ObjectRefGenerator over dynamic returns (python/ray/_raylet.pyx:1138)."""

    def __init__(self, completion_ref: ObjectRef, task_id, _owner: bool = True):
        self._completion = completion_ref
        self._task_id = task_id
        self._i = 0
        self._count: Optional[int] = None
        # Only the ORIGINAL generator owns the stream: a deserialized copy
        # yields borrowed refs and never drop_stream's on GC — each item carries
        # exactly one registration incref, so a second owning consumer would
        # double-decref items the first consumer's refs still pin.
        self._owner = _owner

    @property
    def completed(self) -> ObjectRef:
        """The completion ref (resolves to the item count; raises task errors)."""
        return self._completion

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        from . import global_state

        ctx = global_state.worker()
        while True:
            if self._count is not None:
                if self._i >= self._count:
                    raise StopIteration
                ref = ObjectRef(stream_item_id(self._task_id, self._i),
                                owned=self._owner)
                self._i += 1
                return ref
            item = ObjectRef(stream_item_id(self._task_id, self._i))
            ready, _ = ctx.wait([item, self._completion], 1, None)
            if any(r.id == item.id for r in ready):
                self._i += 1
                return ObjectRef(item.id, owned=self._owner)
            # completion landed first: learn the count (or raise the task error)
            self._count = int(ctx.get(self._completion))

    def handoff(self) -> Tuple:
        """Transfer the stream's REMAINING items to another process: returns
        the (completion, task_id, cursor, count) state for ``adopt`` and
        disowns this copy, so drop-on-GC moves with the state instead of
        firing here while the adopting consumer is still draining. Single
        consumer only: the caller must stop iterating after handoff.

        The completion object is PINNED here (synchronously, before this
        process's owned ref can GC-decref it): the head abandons a stream —
        dropping every item the producer yields from then on — the moment its
        completion object is freed while the task still runs, so without the
        pin the hand-off would race this process's GC and strand the adopter
        mid-stream. ``adopt`` rebuilds the completion as an OWNED ref whose
        GC-decref releases exactly this pin."""
        from . import global_state

        global_state.worker().incref(self._completion.id)
        state = (self._completion, self._task_id, self._i, self._count)
        self._owner = False
        return state

    @classmethod
    def adopt(cls, state: Tuple) -> "ObjectRefGenerator":
        """Rebuild an OWNING generator from ``handoff`` state: resumes at the
        handed-off cursor and takes over drop-on-GC/close for the items the
        original never consumed. The completion ref is rebuilt OWNED so this
        process's GC releases the pin ``handoff`` took."""
        completion, task_id, i, count = state
        g = cls(ObjectRef(completion.id, owned=True), task_id, _owner=True)
        g._i, g._count = i, count
        return g

    def close(self) -> None:
        """Release unconsumed items NOW (same effect as GC'ing the generator):
        the producer is cancelled at its next yield boundary."""
        if not self._owner:
            return
        self._owner = False  # __del__ becomes a no-op; later __next__ borrows
        try:
            from . import global_state

            if global_state.try_worker() is not None:
                global_state.enqueue_gc_action(
                    "drop_stream", (self._task_id, self._i))
        # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
        except Exception:
            pass

    def __reduce__(self):
        return (_rebuild_ref_generator,
                (self._completion, self._task_id, self._i, self._count))

    def __del__(self):
        # release unconsumed items (and anything the producer yields later);
        # queued, never direct — GC may run on a thread holding runtime locks.
        # Borrowed (deserialized) copies never drop: ownership stays with the
        # first consumer.
        if not self._owner:
            return
        try:
            from . import global_state

            if global_state.try_worker() is not None:
                global_state.enqueue_gc_action(
                    "drop_stream", (self._task_id, self._i))
        # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
        except Exception:
            pass


def _rebuild_ref_generator(completion, task_id, i, count):
    g = ObjectRefGenerator(completion, task_id, _owner=False)
    g._i, g._count = i, count
    return g
