"""ObjectRef: a future for a value in the object store.

Capability parity: reference ObjectRef (python/ray/_raylet.pyx) + distributed refcounting
(src/ray/core_worker/reference_count.cc). Ownership model: the driver node coordinator owns
the directory; driver-side refs participate in refcounting via their Python lifetime
(__del__ -> decref). Worker-side refs are borrowed and do not decref (the owner's ref
pins the object for the duration of the borrow).
"""
from __future__ import annotations

from typing import Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "_owned", "__weakref__")

    def __init__(self, oid: ObjectID, owned: bool = False):
        self.id = oid
        self._owned = owned

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def future(self):
        """Return a concurrent.futures.Future resolving to the object's value."""
        from . import global_state

        return global_state.worker().as_future(self)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __reduce__(self):
        # Refs are serialized as borrows; ownership never transfers through pickling.
        return (ObjectRef, (self.id,))

    def __del__(self):
        if self._owned:
            from . import global_state

            try:
                # NEVER call the runtime here: __del__ can run via GC on a thread
                # that already holds the store lock or is mid-pipe-send — the
                # decref is queued and applied by the gc-action drainer
                if global_state.try_worker() is not None:
                    global_state.enqueue_gc_action("decref", self.id)
            except Exception:
                pass

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()
