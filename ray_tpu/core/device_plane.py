"""Device-native tensor transfer between actor processes (the NCCL-channel analogue).

Capability parity: reference python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:54 and python/ray/experimental/channel/
torch_tensor_nccl_channel.py — tensors stay resident on the accelerator and move
peer-to-peer on demand; only a small descriptor rides the control plane.

TPU shape of the idea: each process runs a PJRT *transfer server*
(`jax.experimental.transfer`, the DCN cross-slice transfer engine). A producer
`export()`s a pytree of jax.Arrays, getting a small picklable `DeviceHandle`; any
number of consumer processes `fetch()` it. Fetch arms a one-shot pull on the
producer via a per-process *arm server* (each consumer gets its own transfer uuid
— the PJRT protocol is strictly one pull per uuid), then pulls the buffers
device-to-device: on TPU pods the bytes ride DCN between hosts and never touch
Python, pickle, or the object store; the sandbox CPU backend uses the same socket
bulk-transport path.

Why an arm server instead of arming at export time: a pull consumes its uuid and
a stale uuid poisons the whole connection, so the number of consumers must not be
guessed up front. The arm round-trip is a ~1 KB control message; payload bytes
move exclusively through the transfer server.

Sharding contract: a NamedSharding is re-built on the consumer from (axis names,
mesh shape, partition spec) over `jax.devices()` in default order. When the
consumer cannot host the producer's mesh (fewer devices — e.g. a small decode
pool pulling from a big prefill pool), fetch falls back to a RESHARDING pull:
the producer arms its per-shard pieces, the consumer pulls each piece
device-to-device onto its own devices, and one compiled assemble program
scatters the pieces into an array sharded over a consumer-sized mesh (same axis
names, sizes shrunk to fit). Payload bytes still never touch host pickle.
"""
from __future__ import annotations

import functools
import secrets
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.config import CONFIG


class DevicePlaneError(RuntimeError):
    """Fetch could not complete device-natively; callers fall back to host bytes."""


# ------------------------------------------------------------------ descriptors

@dataclass(frozen=True)
class ArraySpec:
    shape: Tuple[int, ...]
    dtype: str
    sharding: Tuple  # ("single",) | ("named", axis_names, mesh_shape, spec_entries)
    nbytes: int


@dataclass(frozen=True)
class DeviceHandle:
    """Small picklable descriptor of an exported device pytree."""

    arm_host: str
    arm_port: int
    key: bytes
    specs: Tuple[ArraySpec, ...]
    treedef_pickle: bytes  # jax treedefs pickle fine; kept opaque here
    nbytes: int


@dataclass(frozen=True)
class PagedKVHandle:
    """Descriptor of a block-addressable paged export (P/D KV handoff).

    Unlike DeviceHandle (one whole-buffer PJRT pull), the payload is published
    on the striped collective data plane as one segment per flat array, and
    consumers issue ranged multi-stream page pulls against (data_host,
    data_port). The arm channel is kept for control only: liveness probes
    ("stat") and release acks ride it, payload bytes never do."""

    arm_host: str
    arm_port: int
    data_host: str
    data_port: int
    key: bytes
    specs: Tuple[ArraySpec, ...]
    treedef_pickle: bytes
    nbytes: int
    page_bytes: int

    @property
    def n_pages(self) -> int:
        return max(1, -(-self.nbytes // self.page_bytes))

    def segments(self) -> Tuple[Tuple[str, int, int], ...]:
        """(store_key, global_offset, nbytes) per flat array, in spec order —
        the region's address map, derived so the handle stays small."""
        out, off = [], 0
        hexkey = self.key.hex()
        for i, s in enumerate(self.specs):
            out.append((f"pdkv:{hexkey}:{i}", off, s.nbytes))
            off += s.nbytes
        return tuple(out)


def _describe_sharding(arr) -> Tuple:
    sh = getattr(arr, "sharding", None)
    if sh is None:  # host numpy leaf (paged exports accept plain ndarrays)
        return ("single",)
    from jax.sharding import NamedSharding

    if isinstance(sh, NamedSharding) and len(sh.mesh.devices.flat) > 1:
        spec_entries = tuple(
            tuple(e) if isinstance(e, (tuple, list)) else e for e in tuple(sh.spec)
        )
        return ("named", tuple(sh.mesh.axis_names), tuple(sh.mesh.devices.shape),
                spec_entries)
    return ("single",)


def _rebuild_sharding(desc: Tuple):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec, SingleDeviceSharding

    if desc[0] == "named":
        _, axis_names, mesh_shape, spec_entries = desc
        n = int(np.prod(mesh_shape))
        devs = jax.devices()
        if len(devs) < n:
            raise DevicePlaneError(
                f"consumer has {len(devs)} devices, producer mesh needs {n}")
        mesh = Mesh(np.asarray(devs[:n]).reshape(mesh_shape), axis_names)
        spec = PartitionSpec(*spec_entries)
        return NamedSharding(mesh, spec)
    return SingleDeviceSharding(_default_device())


def _fit_target_sharding(desc: Tuple, shape: Tuple[int, ...]):
    """A consumer-sized stand-in for a producer sharding the consumer can't
    host: same axis names and partition spec, mesh sizes shrunk (halving the
    largest axes) until the consumer's devices suffice. Spec axes that no
    longer divide the array dims drop to replicated."""
    import functools
    import operator

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    _, axis_names, mesh_shape, spec_entries = desc
    n = len(jax.devices())
    sizes = list(mesh_shape)
    while functools.reduce(operator.mul, sizes, 1) > n:
        i = max(range(len(sizes)), key=lambda j: sizes[j])
        if sizes[i] <= 1:
            raise DevicePlaneError("cannot fit producer mesh on consumer")
        sizes[i] = sizes[i] // 2 if sizes[i] % 2 == 0 else 1
    total = functools.reduce(operator.mul, sizes, 1)
    mesh = Mesh(np.asarray(jax.devices()[:total]).reshape(sizes), axis_names)
    by_name = dict(zip(axis_names, sizes))

    def _entry_ok(entry, dim):
        names = entry if isinstance(entry, tuple) else (entry,)
        span = functools.reduce(operator.mul, (by_name.get(a, 1) for a in names), 1)
        return dim % span == 0

    entries = []
    for i, entry in enumerate(spec_entries):
        if entry is None or i >= len(shape):
            entries.append(None)
        else:
            entries.append(entry if _entry_ok(entry, shape[i]) else None)
    return NamedSharding(mesh, PartitionSpec(*entries))


@functools.lru_cache(maxsize=256)
def _assemble_program(starts_list: Tuple, block_shape: Tuple, dtype: str, dev):
    """Compiled single-device scatter-assemble: the pieces of ONE target shard
    (already pulled onto their owning device) -> that shard's block. Cached per
    (piece layout, shape, device) so steady-state fetches replay."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    sh = SingleDeviceSharding(dev)

    def build(pieces):
        out = jnp.zeros(block_shape, jnp.dtype(dtype))
        for p, st in zip(pieces, starts_list):
            out = jax.lax.dynamic_update_slice(out, p.astype(out.dtype), st)
        return out

    return jax.jit(build, in_shardings=([sh] * len(starts_list),),
                   out_shardings=sh)


class _ReshardPlan:
    """Where each producer piece lands and how target shards assemble."""

    def __init__(self, target, pieces, groups):
        self.target = target
        # meta-order: (piece_shape, global_starts, owning consumer device)
        self.pieces = pieces
        # one per DISTINCT target shard: ((start, stop) per dim, [devices
        # holding this shard], [piece indices covering it])
        self.groups = groups

    def assemble(self, pulled: List, spec: ArraySpec):
        import jax

        shape = tuple(spec.shape)
        blocks = []
        for key, devs, pidx in self.groups:
            local_shape = tuple(b - a for a, b in key)
            primary = devs[0]
            if len(pidx) == 1 and tuple(self.pieces[pidx[0]][0]) == local_shape:
                block = pulled[pidx[0]]
            else:
                starts_local = tuple(
                    tuple(s - a for s, (a, _b) in zip(self.pieces[i][1], key))
                    for i in pidx)
                prog = _assemble_program(starts_local, local_shape, spec.dtype,
                                         primary)
                block = prog([pulled[i] for i in pidx])
            blocks.append(block)
            for extra in devs[1:]:  # replicated target dims: device-to-device copy
                blocks.append(jax.device_put(block, extra))
        if len(blocks) == 1 and not isinstance(
                self.target, jax.sharding.NamedSharding):
            return blocks[0]
        return jax.make_array_from_single_device_arrays(
            shape, self.target, blocks)


def _reshard_plan(spec: ArraySpec, per_arr: List) -> _ReshardPlan:
    """Assign producer pieces to the consumer devices owning their slices of
    the shrunk-mesh target sharding; raises DevicePlaneError (-> host fallback)
    when the pieces don't nest exactly."""
    import math

    import jax
    from jax.sharding import SingleDeviceSharding

    shape = tuple(spec.shape)
    if spec.sharding[0] != "named":
        dev = jax.devices()[0]
        pieces = [(tuple(ps), tuple(st), dev) for ps, st in per_arr]
        key = tuple((0, d) for d in shape)
        return _ReshardPlan(SingleDeviceSharding(dev), pieces,
                            [(key, [dev], list(range(len(per_arr))))])
    target = _fit_target_sharding(spec.sharding, shape)
    groups: Dict[Tuple, List] = {}
    order: List[Tuple] = []
    for dev, idx in target.devices_indices_map(shape).items():
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, shape))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(dev)
    pieces, assign = [], {key: [] for key in order}
    for pi, (pshape, starts) in enumerate(per_arr):
        rng = tuple((s, s + d) for s, d in zip(starts, pshape))
        home = next(
            (key for key in order
             if all(a >= ka and b <= kb
                    for (a, b), (ka, kb) in zip(rng, key))), None)
        if home is None:
            raise DevicePlaneError(
                "producer shard does not nest inside the consumer sharding")
        assign[home].append(pi)
        pieces.append((tuple(pshape), tuple(starts), groups[home][0]))
    for key, pidx in assign.items():
        vol = sum(math.prod(per_arr[i][0]) for i in pidx)
        tvol = math.prod(b - a for a, b in key) if key else 1
        if vol != tvol:
            raise DevicePlaneError(
                "target shard not exactly covered by producer pieces")
    return _ReshardPlan(target, pieces,
                        [(key, groups[key], assign[key]) for key in order])


def _default_device():
    import jax

    return jax.devices()[0]


def _node_ip() -> str:
    import os

    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        # UDP connect trick: finds the outbound interface without sending.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


# ------------------------------------------------------------------ the plane

class DevicePlane:
    """Per-process transfer endpoint: exports, arms, and pulls device pytrees."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._server = None  # PJRT TransferServer
        self._xfer_addr: Optional[str] = None
        self._arm_listener = None
        self._arm_addr: Optional[Tuple[str, int]] = None
        self._authkey: Optional[bytes] = None
        self._exports: Dict[bytes, List[Any]] = {}  # key -> flat arrays (pinned)
        # Opt-in TTL backstop (ADVICE r4): exports whose consumer might crash
        # without acking (P/D KV handoffs) pass export(ttl_s=...) and get swept
        # here if never released. Exports with a live OWNER that releases them
        # deterministically (device objects freed by the object store,
        # DeviceChannel values released on the next write) pass no TTL and stay
        # pinned until release() — a sweep there would DESTROY live data.
        self._export_deadlines: Dict[bytes, float] = {}
        # paged exports: key -> collective-plane store keys holding the host
        # copy of the KV region (one per flat array); released the same ways
        # _exports is (explicit, consumer ack, TTL sweep)
        self._paged_exports: Dict[bytes, List[str]] = {}
        # release subscribers (engine-level export bookkeeping): fired with the
        # key after ANY release, outside the plane lock
        self._release_listeners: List[Any] = []
        self._ttl_thread: Optional[threading.Thread] = None
        self._conns: Dict[str, Any] = {}  # xfer addr -> TransferConnection
        # arm addr -> pooled control conns (see _control: dial+challenge reuse)
        self._control_pool: Dict[Tuple[str, int], List[Any]] = {}
        self._uuid_counter = secrets.randbits(48) << 14  # process-unique uuid space
        self.counters: Dict[str, int] = {
            "exports": 0, "arms": 0, "pulls": 0, "bytes_pulled": 0, "fallbacks": 0,
        }
        self._disabled_reason: Optional[str] = None
        self._control_disabled_reason: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._server is not None or self._disabled_reason:
            return
        with self._lock:
            if self._server is not None or self._disabled_reason:
                return
            try:
                self._start_locked()
            except Exception as e:  # no transfer support on this backend/build
                self._disabled_reason = f"{type(e).__name__}: {e}"

    def _ensure_control_started(self) -> None:
        """Start just the arm/control channel (authkey + listener). The paged
        KV handoff moves payload over the striped socket data plane, so it
        stays available on backends whose jax build lacks PJRT transfer
        support — only whole-buffer device fetches need the transfer server."""
        if self._arm_listener is not None or self._control_disabled_reason:
            return
        with self._lock:
            if self._arm_listener is not None or self._control_disabled_reason:
                return
            try:
                self._start_control_locked()
            except Exception as e:
                self._control_disabled_reason = f"{type(e).__name__}: {e}"

    def _start_control_locked(self) -> None:
        from ray_tpu.core.secure_transport import make_listener
        from ray_tpu.util.client.server import load_authkey

        authkey = load_authkey()
        if authkey is None:
            # Never MINT a key here: two peers racing generate_authkey() would
            # persist different session keys and every fetch would fail auth.
            # No cluster session -> no plane (callers fall back to host bytes).
            raise RuntimeError(
                "no cluster session authkey (set RAY_TPU_CLIENT_AUTHKEY or "
                "init a cluster first)")
        ip = _node_ip()
        listener = make_listener((ip, 0), backlog=64)
        self._authkey = authkey
        self._arm_listener = listener
        self._arm_addr = (ip, listener.address[1])
        threading.Thread(target=self._arm_loop, daemon=True,
                         name="rt-device-plane-arm").start()

    def _start_locked(self) -> None:
        import jax
        from jax.experimental import transfer

        if self._arm_listener is None:
            self._start_control_locked()
        ip = self._arm_addr[0]
        client = jax.devices()[0].client
        # Explicit socket transport addresses: the default same-host "local" bulk
        # transport is not implemented for all backends (CHECK-fails on CPU), and
        # cross-host always needs routable sockets anyway.
        server = transfer.start_transfer_server(
            client, f"{ip}:0", [f"{ip}:0"])
        self._server = server
        self._xfer_addr = server.address()

    @property
    def available(self) -> bool:
        if not CONFIG.device_plane:
            return False
        self._ensure_started()
        return self._server is not None

    @property
    def paged_available(self) -> bool:
        """Can this process produce/consume paged exports? Needs only the
        control channel + striped data plane, not PJRT transfer support."""
        if not CONFIG.device_plane:
            return False
        self._ensure_control_started()
        return self._arm_listener is not None

    @property
    def disabled_reason(self) -> Optional[str]:
        return self._disabled_reason

    # -- producer side -----------------------------------------------------------

    def export(self, tree: Any, ttl_s: Optional[float] = None) -> DeviceHandle:
        """Register a pytree of jax.Arrays for device-native fetch by peers.

        The plane holds strong references until `release(handle.key)` — exports
        pin device memory, so producers release as soon as consumers are done
        (P/D: when the decode side acks; channels: on next write). ttl_s, when
        given, additionally auto-releases the export after that long — the
        crashed-consumer backstop for fire-and-forget handoffs; leave it None
        for exports an owner releases deterministically.
        """
        if not self.available:
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        flat, treedef = jax.tree.flatten(tree)
        if not flat:
            raise DevicePlaneError("empty pytree")
        specs = tuple(
            ArraySpec(tuple(x.shape), str(x.dtype), _describe_sharding(x), x.nbytes)
            for x in flat
        )
        key = secrets.token_bytes(16)
        with self._lock:
            self._exports[key] = flat
            self.counters["exports"] += 1
            if ttl_s is not None:
                self._export_deadlines[key] = time.monotonic() + ttl_s
                if self._ttl_thread is None:
                    self._ttl_thread = threading.Thread(
                        target=self._ttl_loop, daemon=True,
                        name="rt-device-plane-ttl")
                    self._ttl_thread.start()
        host, port = self._arm_addr
        return DeviceHandle(
            arm_host=host, arm_port=port, key=key, specs=specs,
            treedef_pickle=pickle.dumps(treedef),
            nbytes=sum(s.nbytes for s in specs))

    def export_paged(self, tree: Any, ttl_s: Optional[float] = None,
                     page_bytes: Optional[int] = None) -> PagedKVHandle:
        """Register a pytree as a block-addressable region for ranged,
        multi-stream page pulls (the P/D KV handoff fast path).

        PJRT transfer pulls are whole-buffer only, so the region is gathered
        to host once here and published segment-per-array on the striped
        collective data plane; consumers pull pages concurrently over
        CONFIG.pd_pull_streams sockets, overlapped with their own decode
        bursts. Same lifetime contract as export(): pinned (host-side) until
        release()/consumer ack, with ttl_s as the crashed-consumer backstop.
        """
        if not self.paged_available:
            raise DevicePlaneError(
                self._control_disabled_reason or "device plane disabled")
        import pickle

        import jax
        import numpy as np

        from ray_tpu.util.collective import ring

        flat, treedef = jax.tree.flatten(tree)
        if not flat:
            raise DevicePlaneError("empty pytree")
        specs = tuple(
            ArraySpec(tuple(x.shape), str(x.dtype), _describe_sharding(x), x.nbytes)
            for x in flat
        )
        key = secrets.token_bytes(16)
        page = int(page_bytes or CONFIG.pd_page_bytes)
        # the producer's data server must carry at least one consumer's worth
        # of concurrent page streams without starving collective traffic
        cplane = ring.get_plane(self._authkey,
                                min_streams=max(1, CONFIG.pd_pull_streams))
        seg_keys: List[str] = []
        hexkey = key.hex()
        for i, x in enumerate(flat):
            host_arr = np.ascontiguousarray(np.asarray(x))
            skey = f"pdkv:{hexkey}:{i}"
            # exp=0: the consumer may re-probe ranges; lifetime is ours —
            # retracted on release(), TTL sweep is only the backstop
            cplane.publish(skey, memoryview(host_arr).cast("B"), 0)
            seg_keys.append(skey)
        with self._lock:
            self._paged_exports[key] = seg_keys
            self.counters["exports"] += 1
            self.counters["paged_exports"] = (
                self.counters.get("paged_exports", 0) + 1)
            if ttl_s is not None:
                self._export_deadlines[key] = time.monotonic() + ttl_s
                if self._ttl_thread is None:
                    self._ttl_thread = threading.Thread(
                        target=self._ttl_loop, daemon=True,
                        name="rt-device-plane-ttl")
                    self._ttl_thread.start()
        host, port = self._arm_addr
        return PagedKVHandle(
            arm_host=host, arm_port=port,
            data_host=cplane.addr[0], data_port=cplane.addr[1],
            key=key, specs=specs, treedef_pickle=pickle.dumps(treedef),
            nbytes=sum(s.nbytes for s in specs), page_bytes=page)

    def add_release_listener(self, cb) -> None:
        """Subscribe cb(key: bytes) to export releases (explicit, consumer
        ack over the arm channel, or TTL sweep). Fired outside the plane lock;
        engine-level export bookkeeping syncs on this instead of polling."""
        with self._lock:
            self._release_listeners.append(cb)

    def release(self, key: bytes) -> None:
        with self._lock:
            found = (self._exports.pop(key, None) is not None)
            seg_keys = self._paged_exports.pop(key, None)
            found = found or seg_keys is not None
            self._export_deadlines.pop(key, None)
            listeners = list(self._release_listeners) if found else []
        if seg_keys:
            try:
                from ray_tpu.util.collective import ring

                cplane = ring.get_plane(self._authkey)
                for skey in seg_keys:
                    cplane.retract(skey)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        for cb in listeners:
            try:
                cb(key)
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass

    def _ttl_loop(self, interval_s: float = 30.0) -> None:
        while True:
            time.sleep(interval_s)
            now = time.monotonic()
            with self._lock:
                stale = [k for k, d in self._export_deadlines.items()
                         if now > d]
            for k in stale:
                # through release(): paged store keys retract and release
                # listeners fire for TTL sweeps too
                self.release(k)

    def _arm_loop(self) -> None:
        while True:
            try:
                conn = self._arm_listener.accept()
            except EOFError:
                continue  # one bad/failed dial (TLS probe) must not stop serving
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_arm, args=(conn,), daemon=True,
                             name="rt-device-plane-serve").start()

    def _serve_arm(self, conn) -> None:
        from multiprocessing.connection import deliver_challenge, answer_challenge
        import pickle

        from ray_tpu.core.secure_transport import set_nodelay

        try:
            # control ops are tiny request/response pairs; without NODELAY each
            # one eats a Nagle + delayed-ACK stall (~40 ms on loopback)
            set_nodelay(conn.fileno())
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
            while True:
                op, key = pickle.loads(conn.recv_bytes())
                if op == "release":
                    self.release(key)
                    conn.send_bytes(pickle.dumps(("ok",)))
                    continue
                if op == "stat":
                    # liveness probe for paged fetches: lets the consumer fail
                    # a dead/released export eagerly instead of blocking a
                    # ranged pull on a range that will never publish
                    with self._lock:
                        live = key in self._exports or key in self._paged_exports
                    conn.send_bytes(pickle.dumps(("ok",) if live else ("gone",)))
                    continue
                if op not in ("arm", "arm_shards"):
                    conn.send_bytes(pickle.dumps(("err", f"bad op {op!r}")))
                    continue
                if self._server is None:
                    # control-only start (paged handoff on a backend without
                    # PJRT transfer support): whole-buffer pulls can't arm
                    conn.send_bytes(pickle.dumps(
                        ("err", "no PJRT transfer server")))
                    continue
                with self._lock:
                    flat = self._exports.get(key)
                    if flat is None:
                        conn.send_bytes(pickle.dumps(("gone",)))
                        continue
                    self._uuid_counter += 1
                    uuid = self._uuid_counter
                    self.counters["arms"] += 1
                if op == "arm_shards":
                    # resharding pull: arm the per-shard PIECES so a consumer
                    # with a different device topology can pull them one by
                    # one and reassemble under its own mesh
                    pieces, meta = [], []
                    for arr in flat:
                        per_arr, seen = [], set()
                        for sh in arr.addressable_shards:
                            starts = tuple(int(sl.start or 0) for sl in sh.index)
                            if starts in seen:  # replicated copy of a piece
                                continue
                            seen.add(starts)
                            pieces.append(sh.data)
                            per_arr.append((tuple(sh.data.shape), starts))
                        meta.append(per_arr)
                    self._server.await_pull(uuid, pieces)
                    conn.send_bytes(pickle.dumps(
                        ("ok", self._xfer_addr, uuid, meta)))
                    continue
                # await_pull holds buffer refs in the server until pulled.
                self._server.await_pull(uuid, flat)
                conn.send_bytes(pickle.dumps(("ok", self._xfer_addr, uuid)))
        except (EOFError, OSError, pickle.UnpicklingError):
            pass
        finally:
            try:
                conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    # -- consumer side -----------------------------------------------------------

    def fetch(self, handle: DeviceHandle, release: bool = False) -> Any:
        """Pull an exported pytree device-to-device. Raises DevicePlaneError on any
        failure (producer gone, topology mismatch) — callers fall back to host.

        release=True acks the producer after a successful pull so it drops its
        pinned export immediately (single-consumer handoffs like P/D KV)."""
        if not self.available:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        try:
            try:
                shardings = [_rebuild_sharding(s.sharding) for s in handle.specs]
            except DevicePlaneError:
                # consumer can't host the producer's mesh (e.g. a 2-chip decode
                # pool pulling from a 4-chip prefill pool): per-shard pull +
                # compiled reassembly under a consumer-sized mesh
                return self._fetch_reshard(handle, release)
            xfer_addr, uuid = self._arm(handle)
            avals = [
                jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
                for s, sh in zip(handle.specs, shardings)
            ]
            conn = self._connection(xfer_addr)
            try:
                flat = conn.pull(uuid, avals)
            except Exception:
                # A failed pull poisons the PJRT connection: drop it so the next
                # fetch redials instead of inheriting a dead socket.
                with self._lock:
                    self._conns.pop(xfer_addr, None)
                raise
            with self._lock:
                self.counters["pulls"] += 1
                self.counters["bytes_pulled"] += handle.nbytes
            if release:
                try:
                    self._control(handle, ("release", handle.key))
                # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
                except Exception:
                    pass  # producer TTL-prunes as backstop
            treedef = pickle.loads(handle.treedef_pickle)
            return jax.tree.unflatten(treedef, flat)
        except DevicePlaneError:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise
        except Exception as e:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(f"device fetch failed: {type(e).__name__}: {e}") from e

    def _fetch_reshard(self, handle: DeviceHandle, release: bool) -> Any:
        """Pull a producer's per-shard pieces onto this process's devices and
        assemble them under a consumer-sized sharding — the unequal-topology
        half of the fetch contract (reference analogue: NCCL channels reshard
        between different-size P/D pools,
        experimental/channel/torch_tensor_nccl_channel.py).

        Each piece is pulled STRAIGHT to the consumer device that owns its
        slice of the target sharding (the shrunk-mesh producer spec always
        refines it along the same axes), then assembled per-device — payload
        bytes go producer-device -> owning consumer-device exactly once."""
        import pickle

        import jax
        from jax.sharding import SingleDeviceSharding

        resp = self._control(handle, ("arm_shards", handle.key))
        if resp[0] == "gone":
            raise DevicePlaneError("export released by producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"arm_shards failed: {resp!r}")
        _, xfer_addr, uuid, meta = resp
        plans = [
            _reshard_plan(spec, per_arr)
            for spec, per_arr in zip(handle.specs, meta)
        ]
        avals = [
            jax.ShapeDtypeStruct(shape, spec.dtype,
                                 sharding=SingleDeviceSharding(dev))
            for spec, plan in zip(handle.specs, plans)
            for shape, _starts, dev in plan.pieces
        ]
        conn = self._connection(xfer_addr)
        try:
            flat_pieces = conn.pull(uuid, avals)
        except Exception:
            with self._lock:
                self._conns.pop(xfer_addr, None)
            raise
        with self._lock:
            self.counters["pulls"] += 1
            self.counters["reshard_pulls"] = self.counters.get("reshard_pulls", 0) + 1
            self.counters["bytes_pulled"] += handle.nbytes
        if release:
            try:
                self._control(handle, ("release", handle.key))
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass  # plane TTL-prunes as backstop
        arrays, pos = [], 0
        for spec, plan in zip(handle.specs, plans):
            pieces = flat_pieces[pos:pos + len(plan.pieces)]
            pos += len(plan.pieces)
            arrays.append(plan.assemble(pieces, spec))
        treedef = pickle.loads(handle.treedef_pickle)
        return jax.tree.unflatten(treedef, arrays)

    def fetch_paged(self, handle: PagedKVHandle, release: bool = False,
                    on_done=None) -> "PagedKVFetch":
        """Begin a multi-stream paged pull of an export_paged() region and
        return immediately with the in-flight PagedKVFetch — the caller
        overlaps its own work (decode bursts) with the transfer and collects
        the arrays via result() when it actually needs them.

        Fails EAGERLY (DevicePlaneError raised here) when the export is
        already gone — a liveness probe on the arm channel — so callers can
        fall back to the host path before anything streamed. Mid-transfer
        failures (producer SIGKILL, retraction, deadline) surface as
        DevicePlaneError from wait()/result() within the bounded-probe stall
        window, never as an indefinite hang.

        release=True acks the producer over the arm channel once the last
        page lands (single-consumer handoffs)."""
        if not self.paged_available:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(
                self._control_disabled_reason or "device plane disabled")
        try:
            resp = self._control(handle, ("stat", handle.key))
        except DevicePlaneError:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise
        if resp[0] == "gone":
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError("export was released by the producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"stat failed: {resp!r}")
        return PagedKVFetch(self, handle, release=release, on_done=on_done)

    _CONTROL_POOL_MAX = 4  # pooled arm-channel conns kept per producer

    def _dial_control(self, addr: Tuple[str, int]):
        from ray_tpu.core.secure_transport import dial
        from ray_tpu.util.client.server import load_authkey

        authkey = self._authkey or load_authkey()
        if authkey is None:
            raise DevicePlaneError("no cluster session authkey")
        try:
            return dial(addr, authkey=authkey)
        except Exception as e:
            raise DevicePlaneError(f"producer unreachable: {e}") from e

    def _control(self, handle: DeviceHandle, msg: Tuple) -> Tuple:
        """One control round trip (arm/stat/release) on the producer's arm
        channel. Connections are pooled per producer: every dial pays a TCP
        connect + 2-round-trip authkey challenge, and the paged handoff path
        issues two control ops per request (liveness stat + release ack) — at
        serving rates the handshakes would dominate the ops themselves. A
        stale pooled connection (producer restarted, idle conn reaped) gets
        one retry on a fresh dial; the server arm loop serves any number of
        sequential ops per connection."""
        import pickle

        addr = (handle.arm_host, handle.arm_port)
        payload = pickle.dumps(msg)
        for attempt in (0, 1):
            conn = None
            if attempt == 0:  # the retry always dials fresh
                with self._lock:
                    free = self._control_pool.get(addr)
                    conn = free.pop() if free else None
            from_pool = conn is not None
            if conn is None:
                conn = self._dial_control(addr)
            try:
                conn.send_bytes(payload)
                resp = pickle.loads(conn.recv_bytes())
            except Exception as e:
                try:
                    conn.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
                if from_pool and attempt == 0:
                    continue  # stale pooled conn: retry once on a fresh dial
                raise DevicePlaneError(f"producer unreachable: {e}") from e
            with self._lock:
                pool = self._control_pool.setdefault(addr, [])
                if len(pool) < self._CONTROL_POOL_MAX:
                    pool.append(conn)
                    conn = None
            if conn is not None:
                try:
                    conn.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
            return resp

    def _arm(self, handle: DeviceHandle) -> Tuple[str, int]:
        resp = self._control(handle, ("arm", handle.key))
        if resp[0] == "gone":
            raise DevicePlaneError("export was released by the producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"arm failed: {resp!r}")
        return resp[1], resp[2]

    def _connection(self, xfer_addr: str):
        with self._lock:
            conn = self._conns.get(xfer_addr)
        if conn is not None:
            return conn
        conn = self._server.connect(xfer_addr)
        with self._lock:
            self._conns[xfer_addr] = conn
        return conn

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["exports_live"] = len(self._exports) + len(self._paged_exports)
        return out


_staging_lock = threading.Lock()
_staging_bufs: List[Any] = []


def _staging_checkout(nbytes: int):
    """A staging buffer of at least `nbytes`: the smallest pooled buffer that
    fits, else a fresh uninitialized allocation. Pooled buffers matter on the
    ingest path — a decode replica fetches prefill KV continuously, and a
    fresh 256 MB destination costs a full zero-fill page-fault pass (~halves
    loopback throughput) that a recycled, already-faulted buffer skips."""
    with _staging_lock:
        best = None
        for i, b in enumerate(_staging_bufs):
            if b.nbytes >= nbytes and (
                    best is None or b.nbytes < _staging_bufs[best].nbytes):
                best = i
        if best is not None:
            return _staging_bufs.pop(best)
    import numpy as np

    # np.empty, not bytearray: bytearray(n) memsets the whole region up front
    # before a single page arrives; an uninitialized buffer lets the kernel
    # zero-fault pages under the readv()s instead, overlapped with the
    # network wait
    return np.empty(max(nbytes, 1), dtype=np.uint8)


def _staging_recycle(buf) -> None:
    with _staging_lock:
        if len(_staging_bufs) < max(0, int(CONFIG.pd_staging_buffers)):
            _staging_bufs.append(buf)


class PagedKVFetch:
    """One in-flight paged KV pull: up to CONFIG.pd_pull_streams puller
    threads (clamped to the page count and the host's CPU count — extra
    streams on a small host only add GIL/context-switch churn) stream the
    region's pages into a single host buffer while the consumer keeps
    decoding its active batch. Pages are claimed near-in-order off a shared
    counter, so the streams naturally load-balance across page-size variance
    and socket jitter.

    The destination is checked out of a process-level staging pool; call
    recycle() once the result() arrays have been copied out (device_put /
    jnp.asarray) so the next handoff reuses the already-faulted pages.

    Failure contract: any puller error (producer SIGKILL -> connection reset,
    export retracted mid-transfer -> bounded probe + stat says gone, overall
    CONFIG.pd_fetch_timeout_s deadline) resolves the fetch with a
    DevicePlaneError raised from wait()/result(); pullers use ~1 s bounded
    probes rather than full-op-timeout blocking reads, so the stall is
    detection-bounded, not timeout-bounded."""

    _PROBE_S = 1.0

    def __init__(self, dplane: "DevicePlane", handle: PagedKVHandle,
                 release: bool = False, on_done=None) -> None:
        import os

        from ray_tpu.util.collective import ring

        self._plane = dplane
        self.handle = handle
        self._release = release
        self._on_done = on_done
        self.nbytes = handle.nbytes
        self.page_bytes = handle.page_bytes
        self.n_pages = handle.n_pages
        self._segs = handle.segments()
        self._buf = _staging_checkout(handle.nbytes)
        self._mv = memoryview(self._buf)[:handle.nbytes]
        self._cv = threading.Condition()
        self._next_page = 0
        self._pages_done = 0
        self._error: Optional[DevicePlaneError] = None
        self._cancelled = False
        self._finished = False
        self.t0_wall_ns = time.time_ns()
        self._t0 = time.perf_counter()
        self.dur_s: Optional[float] = None
        self.streams = max(1, min(int(CONFIG.pd_pull_streams), self.n_pages,
                                  max(2, os.cpu_count() or 1)))
        self._cplane = ring.get_plane(dplane._authkey, min_streams=self.streams)
        for i in range(self.streams):
            threading.Thread(target=self._pull_loop, daemon=True,
                             name=f"rt-pd-pull-{i}").start()

    # -- puller side -------------------------------------------------------------

    def _pull_loop(self) -> None:
        from ray_tpu.util.fault_injection import fail_point

        addr = (self.handle.data_host, self.handle.data_port)
        deadline = self._t0 + float(CONFIG.pd_fetch_timeout_s)
        # claim contiguous RUNS of pages, not single pages: a failure kills the
        # whole fetch (there is no per-page retry), so page granularity buys
        # nothing per-claim — but every ranged pull costs a request/ok/go
        # handshake, and coalescing a stream's adjacent pages into one pull
        # amortizes it. ~4 claims per stream keeps the tail load-balanced.
        run_pages = max(1, -(-self.n_pages // (self.streams * 4)))
        while True:
            with self._cv:
                if (self._error is not None or self._cancelled
                        or self._next_page >= self.n_pages):
                    return
                page = self._next_page
                run = min(run_pages, self.n_pages - page)
                self._next_page += run
            try:
                # chaos site: armed with mode=delay this stretches the handoff
                # window (SIGKILL-the-producer tests), mode=error simulates a
                # torn pull
                fail_point("llm.pd.handoff", page=page,
                           key=self.handle.key.hex())
                self._pull_range(addr, page, run, deadline)
            except BaseException as e:
                err = e if isinstance(e, DevicePlaneError) else DevicePlaneError(
                    f"paged KV pull failed: {type(e).__name__}: {e}")
                if err is not e:
                    err.__cause__ = e
                first = False
                with self._cv:
                    if self._error is None and not self._finished:
                        self._error = err
                        first = True
                    self._cv.notify_all()
                if first:
                    self._resolve(ok=False)
                return
            done = False
            with self._cv:
                self._pages_done += run
                if self._pages_done >= self.n_pages and not self._finished:
                    self.dur_s = time.perf_counter() - self._t0
                    done = True
                self._cv.notify_all()
            if done:
                self._resolve(ok=True)
                return

    def _pull_range(self, addr, page: int, n_run: int, deadline: float) -> None:
        start = page * self.page_bytes
        end = min(start + n_run * self.page_bytes, self.nbytes)
        for skey, seg_off, seg_len in self._segs:
            lo, hi = max(start, seg_off), min(end, seg_off + seg_len)
            if lo >= hi:
                continue
            while True:
                with self._cv:
                    if self._error is not None or self._cancelled:
                        return
                n = self._cplane.pull_into(addr, skey, lo - seg_off, hi - lo,
                                           self._mv[lo:hi],
                                           timeout=self._PROBE_S)
                if n is not None:
                    break
                # bounded-probe miss: the range is published up front, so a
                # miss means the export was retracted (or the producer is
                # wedged) — probe liveness instead of pinning an op timeout
                resp = self._plane._control(self.handle,
                                            ("stat", self.handle.key))
                if resp[0] != "ok":
                    raise DevicePlaneError(
                        "export released by producer mid-transfer")
                if time.perf_counter() > deadline:
                    raise DevicePlaneError(
                        f"paged KV fetch exceeded "
                        f"{CONFIG.pd_fetch_timeout_s}s deadline")

    def _resolve(self, ok: bool) -> None:
        with self._cv:
            if self._finished:
                return
            self._finished = True
        with self._plane._lock:
            if ok:
                self._plane.counters["pulls"] += 1
                self._plane.counters["bytes_pulled"] += self.nbytes
                self._plane.counters["paged_pulls"] = (
                    self._plane.counters.get("paged_pulls", 0) + 1)
            else:
                self._plane.counters["fallbacks"] += 1
        if ok and self._release:
            self._ack_release()
        cb = self._on_done
        if cb is not None:
            try:
                cb()
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass

    def _ack_release(self) -> None:
        try:
            self._plane._control(self.handle, ("release", self.handle.key))
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass  # producer TTL-prunes as backstop

    # -- consumer side -----------------------------------------------------------

    def failed(self) -> Optional[DevicePlaneError]:
        with self._cv:
            return self._error

    def ready(self) -> bool:
        """All pages landed (does not raise; pair with failed())."""
        with self._cv:
            return self._error is None and self._pages_done >= self.n_pages

    def pages_done(self) -> int:
        with self._cv:
            return self._pages_done

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every page landed; raises DevicePlaneError on transfer
        failure or timeout."""
        deadline = time.monotonic() + (
            float(CONFIG.pd_fetch_timeout_s) if timeout is None else timeout)
        with self._cv:
            while True:
                if self._error is not None:
                    raise self._error
                if self._pages_done >= self.n_pages:
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    raise DevicePlaneError(
                        "timed out waiting for paged KV fetch")
                self._cv.wait(min(left, 1.0))

    def result(self, timeout: Optional[float] = None) -> Any:
        """The fetched pytree as zero-copy numpy views over the landed buffer
        (consumers device_put / jnp.asarray what they install)."""
        import pickle

        import jax
        import numpy as np

        self.wait(timeout)
        arrays = []
        for (skey, off, ln), spec in zip(self._segs, self.handle.specs):
            dt = np.dtype(spec.dtype)
            arrays.append(
                np.frombuffer(self._buf, dtype=dt, count=ln // dt.itemsize,
                              offset=off).reshape(spec.shape))
        treedef = pickle.loads(self.handle.treedef_pickle)
        return jax.tree.unflatten(treedef, arrays)

    def cancel(self, release: bool = True) -> None:
        """Abandon the transfer (consumer aborted the request): pullers stop
        at the next page/probe boundary; release=True still acks the producer
        so the export unpins without waiting for the TTL backstop."""
        with self._cv:
            if self._finished:
                return
            self._cancelled = True
            self._finished = True
            self._cv.notify_all()
        if release:
            self._ack_release()

    def recycle(self) -> None:
        """Return the staging buffer to the process pool. Call ONLY after the
        result() views have been copied out — they alias the buffer and the
        next fetch will overwrite it. No-op for a cancelled or failed fetch
        (a straggler puller may still be landing bytes into the buffer) and
        on double-recycle."""
        with self._cv:
            if (not self._finished or self._cancelled
                    or self._error is not None or self._buf is None):
                return
            buf, self._buf, self._mv = self._buf, None, None
        _staging_recycle(buf)


def release_remote(handle) -> None:
    """Release an export by dialing the exporting process's arm channel
    directly — pool-safe: a pool routes method calls p2c across replicas, so
    'release via the handle that prefilled' cannot be expressed as a
    deployment call, but the arm address on the handle pins the right
    process. Best-effort; raises DevicePlaneError only when no authkey/dial.
    """
    plane()._control(handle, ("release", handle.key))


_plane: Optional[DevicePlane] = None
_plane_lock = threading.Lock()


def plane() -> DevicePlane:
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = DevicePlane()
    return _plane
