"""Device-native tensor transfer between actor processes (the NCCL-channel analogue).

Capability parity: reference python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:54 and python/ray/experimental/channel/
torch_tensor_nccl_channel.py — tensors stay resident on the accelerator and move
peer-to-peer on demand; only a small descriptor rides the control plane.

TPU shape of the idea: each process runs a PJRT *transfer server*
(`jax.experimental.transfer`, the DCN cross-slice transfer engine). A producer
`export()`s a pytree of jax.Arrays, getting a small picklable `DeviceHandle`; any
number of consumer processes `fetch()` it. Fetch arms a one-shot pull on the
producer via a per-process *arm server* (each consumer gets its own transfer uuid
— the PJRT protocol is strictly one pull per uuid), then pulls the buffers
device-to-device: on TPU pods the bytes ride DCN between hosts and never touch
Python, pickle, or the object store; the sandbox CPU backend uses the same socket
bulk-transport path.

Why an arm server instead of arming at export time: a pull consumes its uuid and
a stale uuid poisons the whole connection, so the number of consumers must not be
guessed up front. The arm round-trip is a ~1 KB control message; payload bytes
move exclusively through the transfer server.

Sharding contract: a NamedSharding is re-built on the consumer from (axis names,
mesh shape, partition spec) over `jax.devices()` in default order. When the
consumer cannot host the producer's mesh (fewer devices — e.g. a small decode
pool pulling from a big prefill pool), fetch falls back to a RESHARDING pull:
the producer arms its per-shard pieces, the consumer pulls each piece
device-to-device onto its own devices, and one compiled assemble program
scatters the pieces into an array sharded over a consumer-sized mesh (same axis
names, sizes shrunk to fit). Payload bytes still never touch host pickle.
"""
from __future__ import annotations

import functools
import secrets
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.config import CONFIG


class DevicePlaneError(RuntimeError):
    """Fetch could not complete device-natively; callers fall back to host bytes."""


# ------------------------------------------------------------------ descriptors

@dataclass(frozen=True)
class ArraySpec:
    shape: Tuple[int, ...]
    dtype: str
    sharding: Tuple  # ("single",) | ("named", axis_names, mesh_shape, spec_entries)
    nbytes: int


@dataclass(frozen=True)
class DeviceHandle:
    """Small picklable descriptor of an exported device pytree."""

    arm_host: str
    arm_port: int
    key: bytes
    specs: Tuple[ArraySpec, ...]
    treedef_pickle: bytes  # jax treedefs pickle fine; kept opaque here
    nbytes: int


def _describe_sharding(arr) -> Tuple:
    from jax.sharding import NamedSharding

    sh = arr.sharding
    if isinstance(sh, NamedSharding) and len(sh.mesh.devices.flat) > 1:
        spec_entries = tuple(
            tuple(e) if isinstance(e, (tuple, list)) else e for e in tuple(sh.spec)
        )
        return ("named", tuple(sh.mesh.axis_names), tuple(sh.mesh.devices.shape),
                spec_entries)
    return ("single",)


def _rebuild_sharding(desc: Tuple):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec, SingleDeviceSharding

    if desc[0] == "named":
        _, axis_names, mesh_shape, spec_entries = desc
        n = int(np.prod(mesh_shape))
        devs = jax.devices()
        if len(devs) < n:
            raise DevicePlaneError(
                f"consumer has {len(devs)} devices, producer mesh needs {n}")
        mesh = Mesh(np.asarray(devs[:n]).reshape(mesh_shape), axis_names)
        spec = PartitionSpec(*spec_entries)
        return NamedSharding(mesh, spec)
    return SingleDeviceSharding(_default_device())


def _fit_target_sharding(desc: Tuple, shape: Tuple[int, ...]):
    """A consumer-sized stand-in for a producer sharding the consumer can't
    host: same axis names and partition spec, mesh sizes shrunk (halving the
    largest axes) until the consumer's devices suffice. Spec axes that no
    longer divide the array dims drop to replicated."""
    import functools
    import operator

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    _, axis_names, mesh_shape, spec_entries = desc
    n = len(jax.devices())
    sizes = list(mesh_shape)
    while functools.reduce(operator.mul, sizes, 1) > n:
        i = max(range(len(sizes)), key=lambda j: sizes[j])
        if sizes[i] <= 1:
            raise DevicePlaneError("cannot fit producer mesh on consumer")
        sizes[i] = sizes[i] // 2 if sizes[i] % 2 == 0 else 1
    total = functools.reduce(operator.mul, sizes, 1)
    mesh = Mesh(np.asarray(jax.devices()[:total]).reshape(sizes), axis_names)
    by_name = dict(zip(axis_names, sizes))

    def _entry_ok(entry, dim):
        names = entry if isinstance(entry, tuple) else (entry,)
        span = functools.reduce(operator.mul, (by_name.get(a, 1) for a in names), 1)
        return dim % span == 0

    entries = []
    for i, entry in enumerate(spec_entries):
        if entry is None or i >= len(shape):
            entries.append(None)
        else:
            entries.append(entry if _entry_ok(entry, shape[i]) else None)
    return NamedSharding(mesh, PartitionSpec(*entries))


@functools.lru_cache(maxsize=256)
def _assemble_program(starts_list: Tuple, block_shape: Tuple, dtype: str, dev):
    """Compiled single-device scatter-assemble: the pieces of ONE target shard
    (already pulled onto their owning device) -> that shard's block. Cached per
    (piece layout, shape, device) so steady-state fetches replay."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    sh = SingleDeviceSharding(dev)

    def build(pieces):
        out = jnp.zeros(block_shape, jnp.dtype(dtype))
        for p, st in zip(pieces, starts_list):
            out = jax.lax.dynamic_update_slice(out, p.astype(out.dtype), st)
        return out

    return jax.jit(build, in_shardings=([sh] * len(starts_list),),
                   out_shardings=sh)


class _ReshardPlan:
    """Where each producer piece lands and how target shards assemble."""

    def __init__(self, target, pieces, groups):
        self.target = target
        # meta-order: (piece_shape, global_starts, owning consumer device)
        self.pieces = pieces
        # one per DISTINCT target shard: ((start, stop) per dim, [devices
        # holding this shard], [piece indices covering it])
        self.groups = groups

    def assemble(self, pulled: List, spec: ArraySpec):
        import jax

        shape = tuple(spec.shape)
        blocks = []
        for key, devs, pidx in self.groups:
            local_shape = tuple(b - a for a, b in key)
            primary = devs[0]
            if len(pidx) == 1 and tuple(self.pieces[pidx[0]][0]) == local_shape:
                block = pulled[pidx[0]]
            else:
                starts_local = tuple(
                    tuple(s - a for s, (a, _b) in zip(self.pieces[i][1], key))
                    for i in pidx)
                prog = _assemble_program(starts_local, local_shape, spec.dtype,
                                         primary)
                block = prog([pulled[i] for i in pidx])
            blocks.append(block)
            for extra in devs[1:]:  # replicated target dims: device-to-device copy
                blocks.append(jax.device_put(block, extra))
        if len(blocks) == 1 and not isinstance(
                self.target, jax.sharding.NamedSharding):
            return blocks[0]
        return jax.make_array_from_single_device_arrays(
            shape, self.target, blocks)


def _reshard_plan(spec: ArraySpec, per_arr: List) -> _ReshardPlan:
    """Assign producer pieces to the consumer devices owning their slices of
    the shrunk-mesh target sharding; raises DevicePlaneError (-> host fallback)
    when the pieces don't nest exactly."""
    import math

    import jax
    from jax.sharding import SingleDeviceSharding

    shape = tuple(spec.shape)
    if spec.sharding[0] != "named":
        dev = jax.devices()[0]
        pieces = [(tuple(ps), tuple(st), dev) for ps, st in per_arr]
        key = tuple((0, d) for d in shape)
        return _ReshardPlan(SingleDeviceSharding(dev), pieces,
                            [(key, [dev], list(range(len(per_arr))))])
    target = _fit_target_sharding(spec.sharding, shape)
    groups: Dict[Tuple, List] = {}
    order: List[Tuple] = []
    for dev, idx in target.devices_indices_map(shape).items():
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, shape))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(dev)
    pieces, assign = [], {key: [] for key in order}
    for pi, (pshape, starts) in enumerate(per_arr):
        rng = tuple((s, s + d) for s, d in zip(starts, pshape))
        home = next(
            (key for key in order
             if all(a >= ka and b <= kb
                    for (a, b), (ka, kb) in zip(rng, key))), None)
        if home is None:
            raise DevicePlaneError(
                "producer shard does not nest inside the consumer sharding")
        assign[home].append(pi)
        pieces.append((tuple(pshape), tuple(starts), groups[home][0]))
    for key, pidx in assign.items():
        vol = sum(math.prod(per_arr[i][0]) for i in pidx)
        tvol = math.prod(b - a for a, b in key) if key else 1
        if vol != tvol:
            raise DevicePlaneError(
                "target shard not exactly covered by producer pieces")
    return _ReshardPlan(target, pieces,
                        [(key, groups[key], assign[key]) for key in order])


def _default_device():
    import jax

    return jax.devices()[0]


def _node_ip() -> str:
    import os

    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        # UDP connect trick: finds the outbound interface without sending.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


# ------------------------------------------------------------------ the plane

class DevicePlane:
    """Per-process transfer endpoint: exports, arms, and pulls device pytrees."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._server = None  # PJRT TransferServer
        self._xfer_addr: Optional[str] = None
        self._arm_listener = None
        self._arm_addr: Optional[Tuple[str, int]] = None
        self._authkey: Optional[bytes] = None
        self._exports: Dict[bytes, List[Any]] = {}  # key -> flat arrays (pinned)
        # Opt-in TTL backstop (ADVICE r4): exports whose consumer might crash
        # without acking (P/D KV handoffs) pass export(ttl_s=...) and get swept
        # here if never released. Exports with a live OWNER that releases them
        # deterministically (device objects freed by the object store,
        # DeviceChannel values released on the next write) pass no TTL and stay
        # pinned until release() — a sweep there would DESTROY live data.
        self._export_deadlines: Dict[bytes, float] = {}
        self._ttl_thread: Optional[threading.Thread] = None
        self._conns: Dict[str, Any] = {}  # xfer addr -> TransferConnection
        self._uuid_counter = secrets.randbits(48) << 14  # process-unique uuid space
        self.counters: Dict[str, int] = {
            "exports": 0, "arms": 0, "pulls": 0, "bytes_pulled": 0, "fallbacks": 0,
        }
        self._disabled_reason: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._server is not None or self._disabled_reason:
            return
        with self._lock:
            if self._server is not None or self._disabled_reason:
                return
            try:
                self._start_locked()
            except Exception as e:  # no transfer support on this backend/build
                self._disabled_reason = f"{type(e).__name__}: {e}"

    def _start_locked(self) -> None:
        import jax
        from jax.experimental import transfer

        ip = _node_ip()
        client = jax.devices()[0].client
        # Explicit socket transport addresses: the default same-host "local" bulk
        # transport is not implemented for all backends (CHECK-fails on CPU), and
        # cross-host always needs routable sockets anyway.
        from ray_tpu.util.client.server import load_authkey

        authkey = load_authkey()
        if authkey is None:
            # Never MINT a key here: two peers racing generate_authkey() would
            # persist different session keys and every fetch would fail auth.
            # No cluster session -> no plane (callers fall back to host bytes).
            raise RuntimeError(
                "no cluster session authkey (set RAY_TPU_CLIENT_AUTHKEY or "
                "init a cluster first)")
        server = transfer.start_transfer_server(
            client, f"{ip}:0", [f"{ip}:0"])
        addr = server.address()
        self._authkey = authkey
        from ray_tpu.core.secure_transport import make_listener

        listener = make_listener((ip, 0), backlog=64)
        self._server = server
        self._xfer_addr = addr
        self._arm_listener = listener
        self._arm_addr = (ip, listener.address[1])
        threading.Thread(target=self._arm_loop, daemon=True,
                         name="rt-device-plane-arm").start()

    @property
    def available(self) -> bool:
        if not CONFIG.device_plane:
            return False
        self._ensure_started()
        return self._server is not None

    @property
    def disabled_reason(self) -> Optional[str]:
        return self._disabled_reason

    # -- producer side -----------------------------------------------------------

    def export(self, tree: Any, ttl_s: Optional[float] = None) -> DeviceHandle:
        """Register a pytree of jax.Arrays for device-native fetch by peers.

        The plane holds strong references until `release(handle.key)` — exports
        pin device memory, so producers release as soon as consumers are done
        (P/D: when the decode side acks; channels: on next write). ttl_s, when
        given, additionally auto-releases the export after that long — the
        crashed-consumer backstop for fire-and-forget handoffs; leave it None
        for exports an owner releases deterministically.
        """
        if not self.available:
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        flat, treedef = jax.tree.flatten(tree)
        if not flat:
            raise DevicePlaneError("empty pytree")
        specs = tuple(
            ArraySpec(tuple(x.shape), str(x.dtype), _describe_sharding(x), x.nbytes)
            for x in flat
        )
        key = secrets.token_bytes(16)
        with self._lock:
            self._exports[key] = flat
            self.counters["exports"] += 1
            if ttl_s is not None:
                self._export_deadlines[key] = time.monotonic() + ttl_s
                if self._ttl_thread is None:
                    self._ttl_thread = threading.Thread(
                        target=self._ttl_loop, daemon=True,
                        name="rt-device-plane-ttl")
                    self._ttl_thread.start()
        host, port = self._arm_addr
        return DeviceHandle(
            arm_host=host, arm_port=port, key=key, specs=specs,
            treedef_pickle=pickle.dumps(treedef),
            nbytes=sum(s.nbytes for s in specs))

    def release(self, key: bytes) -> None:
        with self._lock:
            self._exports.pop(key, None)
            self._export_deadlines.pop(key, None)

    def _ttl_loop(self, interval_s: float = 30.0) -> None:
        while True:
            time.sleep(interval_s)
            now = time.monotonic()
            with self._lock:
                stale = [k for k, d in self._export_deadlines.items()
                         if now > d]
                for k in stale:
                    self._exports.pop(k, None)
                    self._export_deadlines.pop(k, None)

    def _arm_loop(self) -> None:
        while True:
            try:
                conn = self._arm_listener.accept()
            except EOFError:
                continue  # one bad/failed dial (TLS probe) must not stop serving
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_arm, args=(conn,), daemon=True,
                             name="rt-device-plane-serve").start()

    def _serve_arm(self, conn) -> None:
        from multiprocessing.connection import deliver_challenge, answer_challenge
        import pickle

        try:
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
            while True:
                op, key = pickle.loads(conn.recv_bytes())
                if op == "release":
                    self.release(key)
                    conn.send_bytes(pickle.dumps(("ok",)))
                    continue
                if op not in ("arm", "arm_shards"):
                    conn.send_bytes(pickle.dumps(("err", f"bad op {op!r}")))
                    continue
                with self._lock:
                    flat = self._exports.get(key)
                    if flat is None:
                        conn.send_bytes(pickle.dumps(("gone",)))
                        continue
                    self._uuid_counter += 1
                    uuid = self._uuid_counter
                    self.counters["arms"] += 1
                if op == "arm_shards":
                    # resharding pull: arm the per-shard PIECES so a consumer
                    # with a different device topology can pull them one by
                    # one and reassemble under its own mesh
                    pieces, meta = [], []
                    for arr in flat:
                        per_arr, seen = [], set()
                        for sh in arr.addressable_shards:
                            starts = tuple(int(sl.start or 0) for sl in sh.index)
                            if starts in seen:  # replicated copy of a piece
                                continue
                            seen.add(starts)
                            pieces.append(sh.data)
                            per_arr.append((tuple(sh.data.shape), starts))
                        meta.append(per_arr)
                    self._server.await_pull(uuid, pieces)
                    conn.send_bytes(pickle.dumps(
                        ("ok", self._xfer_addr, uuid, meta)))
                    continue
                # await_pull holds buffer refs in the server until pulled.
                self._server.await_pull(uuid, flat)
                conn.send_bytes(pickle.dumps(("ok", self._xfer_addr, uuid)))
        except (EOFError, OSError, pickle.UnpicklingError):
            pass
        finally:
            try:
                conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    # -- consumer side -----------------------------------------------------------

    def fetch(self, handle: DeviceHandle, release: bool = False) -> Any:
        """Pull an exported pytree device-to-device. Raises DevicePlaneError on any
        failure (producer gone, topology mismatch) — callers fall back to host.

        release=True acks the producer after a successful pull so it drops its
        pinned export immediately (single-consumer handoffs like P/D KV)."""
        if not self.available:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        try:
            try:
                shardings = [_rebuild_sharding(s.sharding) for s in handle.specs]
            except DevicePlaneError:
                # consumer can't host the producer's mesh (e.g. a 2-chip decode
                # pool pulling from a 4-chip prefill pool): per-shard pull +
                # compiled reassembly under a consumer-sized mesh
                return self._fetch_reshard(handle, release)
            xfer_addr, uuid = self._arm(handle)
            avals = [
                jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
                for s, sh in zip(handle.specs, shardings)
            ]
            conn = self._connection(xfer_addr)
            try:
                flat = conn.pull(uuid, avals)
            except Exception:
                # A failed pull poisons the PJRT connection: drop it so the next
                # fetch redials instead of inheriting a dead socket.
                with self._lock:
                    self._conns.pop(xfer_addr, None)
                raise
            with self._lock:
                self.counters["pulls"] += 1
                self.counters["bytes_pulled"] += handle.nbytes
            if release:
                try:
                    self._control(handle, ("release", handle.key))
                # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
                except Exception:
                    pass  # producer TTL-prunes as backstop
            treedef = pickle.loads(handle.treedef_pickle)
            return jax.tree.unflatten(treedef, flat)
        except DevicePlaneError:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise
        except Exception as e:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(f"device fetch failed: {type(e).__name__}: {e}") from e

    def _fetch_reshard(self, handle: DeviceHandle, release: bool) -> Any:
        """Pull a producer's per-shard pieces onto this process's devices and
        assemble them under a consumer-sized sharding — the unequal-topology
        half of the fetch contract (reference analogue: NCCL channels reshard
        between different-size P/D pools,
        experimental/channel/torch_tensor_nccl_channel.py).

        Each piece is pulled STRAIGHT to the consumer device that owns its
        slice of the target sharding (the shrunk-mesh producer spec always
        refines it along the same axes), then assembled per-device — payload
        bytes go producer-device -> owning consumer-device exactly once."""
        import pickle

        import jax
        from jax.sharding import SingleDeviceSharding

        resp = self._control(handle, ("arm_shards", handle.key))
        if resp[0] == "gone":
            raise DevicePlaneError("export released by producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"arm_shards failed: {resp!r}")
        _, xfer_addr, uuid, meta = resp
        plans = [
            _reshard_plan(spec, per_arr)
            for spec, per_arr in zip(handle.specs, meta)
        ]
        avals = [
            jax.ShapeDtypeStruct(shape, spec.dtype,
                                 sharding=SingleDeviceSharding(dev))
            for spec, plan in zip(handle.specs, plans)
            for shape, _starts, dev in plan.pieces
        ]
        conn = self._connection(xfer_addr)
        try:
            flat_pieces = conn.pull(uuid, avals)
        except Exception:
            with self._lock:
                self._conns.pop(xfer_addr, None)
            raise
        with self._lock:
            self.counters["pulls"] += 1
            self.counters["reshard_pulls"] = self.counters.get("reshard_pulls", 0) + 1
            self.counters["bytes_pulled"] += handle.nbytes
        if release:
            try:
                self._control(handle, ("release", handle.key))
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass  # plane TTL-prunes as backstop
        arrays, pos = [], 0
        for spec, plan in zip(handle.specs, plans):
            pieces = flat_pieces[pos:pos + len(plan.pieces)]
            pos += len(plan.pieces)
            arrays.append(plan.assemble(pieces, spec))
        treedef = pickle.loads(handle.treedef_pickle)
        return jax.tree.unflatten(treedef, arrays)

    def _control(self, handle: DeviceHandle, msg: Tuple) -> Tuple:
        import pickle

        from ray_tpu.core.secure_transport import dial
        from ray_tpu.util.client.server import load_authkey

        authkey = self._authkey or load_authkey()
        if authkey is None:
            raise DevicePlaneError("no cluster session authkey")
        try:
            conn = dial((handle.arm_host, handle.arm_port), authkey=authkey)
        except Exception as e:
            raise DevicePlaneError(f"producer unreachable: {e}") from e
        try:
            conn.send_bytes(pickle.dumps(msg))
            return pickle.loads(conn.recv_bytes())
        finally:
            try:
                conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    def _arm(self, handle: DeviceHandle) -> Tuple[str, int]:
        resp = self._control(handle, ("arm", handle.key))
        if resp[0] == "gone":
            raise DevicePlaneError("export was released by the producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"arm failed: {resp!r}")
        return resp[1], resp[2]

    def _connection(self, xfer_addr: str):
        with self._lock:
            conn = self._conns.get(xfer_addr)
        if conn is not None:
            return conn
        conn = self._server.connect(xfer_addr)
        with self._lock:
            self._conns[xfer_addr] = conn
        return conn

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["exports_live"] = len(self._exports)
        return out


_plane: Optional[DevicePlane] = None
_plane_lock = threading.Lock()


def plane() -> DevicePlane:
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = DevicePlane()
    return _plane
