"""Device-native tensor transfer between actor processes (the NCCL-channel analogue).

Capability parity: reference python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:54 and python/ray/experimental/channel/
torch_tensor_nccl_channel.py — tensors stay resident on the accelerator and move
peer-to-peer on demand; only a small descriptor rides the control plane.

TPU shape of the idea: each process runs a PJRT *transfer server*
(`jax.experimental.transfer`, the DCN cross-slice transfer engine). A producer
`export()`s a pytree of jax.Arrays, getting a small picklable `DeviceHandle`; any
number of consumer processes `fetch()` it. Fetch arms a one-shot pull on the
producer via a per-process *arm server* (each consumer gets its own transfer uuid
— the PJRT protocol is strictly one pull per uuid), then pulls the buffers
device-to-device: on TPU pods the bytes ride DCN between hosts and never touch
Python, pickle, or the object store; the sandbox CPU backend uses the same socket
bulk-transport path.

Why an arm server instead of arming at export time: a pull consumes its uuid and
a stale uuid poisons the whole connection, so the number of consumers must not be
guessed up front. The arm round-trip is a ~1 KB control message; payload bytes
move exclusively through the transfer server.

Sharding contract: a NamedSharding is re-built on the consumer from (axis names,
mesh shape, partition spec) over `jax.devices()` in default order — producer and
consumer must see identically-shaped device sets (true for P/D pools on same-size
slices and for the CPU test mesh). Anything else falls back to the host path at
the call site.
"""
from __future__ import annotations

import secrets
import socket
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.config import CONFIG


class DevicePlaneError(RuntimeError):
    """Fetch could not complete device-natively; callers fall back to host bytes."""


# ------------------------------------------------------------------ descriptors

@dataclass(frozen=True)
class ArraySpec:
    shape: Tuple[int, ...]
    dtype: str
    sharding: Tuple  # ("single",) | ("named", axis_names, mesh_shape, spec_entries)
    nbytes: int


@dataclass(frozen=True)
class DeviceHandle:
    """Small picklable descriptor of an exported device pytree."""

    arm_host: str
    arm_port: int
    key: bytes
    specs: Tuple[ArraySpec, ...]
    treedef_pickle: bytes  # jax treedefs pickle fine; kept opaque here
    nbytes: int


def _describe_sharding(arr) -> Tuple:
    from jax.sharding import NamedSharding

    sh = arr.sharding
    if isinstance(sh, NamedSharding) and len(sh.mesh.devices.flat) > 1:
        spec_entries = tuple(
            tuple(e) if isinstance(e, (tuple, list)) else e for e in tuple(sh.spec)
        )
        return ("named", tuple(sh.mesh.axis_names), tuple(sh.mesh.devices.shape),
                spec_entries)
    return ("single",)


def _rebuild_sharding(desc: Tuple):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec, SingleDeviceSharding

    if desc[0] == "named":
        _, axis_names, mesh_shape, spec_entries = desc
        n = int(np.prod(mesh_shape))
        devs = jax.devices()
        if len(devs) < n:
            raise DevicePlaneError(
                f"consumer has {len(devs)} devices, producer mesh needs {n}")
        mesh = Mesh(np.asarray(devs[:n]).reshape(mesh_shape), axis_names)
        spec = PartitionSpec(*spec_entries)
        return NamedSharding(mesh, spec)
    return SingleDeviceSharding(_default_device())


def _default_device():
    import jax

    return jax.devices()[0]


def _node_ip() -> str:
    import os

    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        # UDP connect trick: finds the outbound interface without sending.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


# ------------------------------------------------------------------ the plane

class DevicePlane:
    """Per-process transfer endpoint: exports, arms, and pulls device pytrees."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._server = None  # PJRT TransferServer
        self._xfer_addr: Optional[str] = None
        self._arm_listener = None
        self._arm_addr: Optional[Tuple[str, int]] = None
        self._authkey: Optional[bytes] = None
        self._exports: Dict[bytes, Tuple[List[Any], bytes]] = {}  # key -> (flat, treedef)
        self._conns: Dict[str, Any] = {}  # xfer addr -> TransferConnection
        self._uuid_counter = secrets.randbits(48) << 14  # process-unique uuid space
        self.counters: Dict[str, int] = {
            "exports": 0, "arms": 0, "pulls": 0, "bytes_pulled": 0, "fallbacks": 0,
        }
        self._disabled_reason: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._server is not None or self._disabled_reason:
            return
        with self._lock:
            if self._server is not None or self._disabled_reason:
                return
            try:
                self._start_locked()
            except Exception as e:  # no transfer support on this backend/build
                self._disabled_reason = f"{type(e).__name__}: {e}"

    def _start_locked(self) -> None:
        import jax
        from jax.experimental import transfer

        ip = _node_ip()
        client = jax.devices()[0].client
        # Explicit socket transport addresses: the default same-host "local" bulk
        # transport is not implemented for all backends (CHECK-fails on CPU), and
        # cross-host always needs routable sockets anyway.
        from ray_tpu.util.client.server import load_authkey

        authkey = load_authkey()
        if authkey is None:
            # Never MINT a key here: two peers racing generate_authkey() would
            # persist different session keys and every fetch would fail auth.
            # No cluster session -> no plane (callers fall back to host bytes).
            raise RuntimeError(
                "no cluster session authkey (set RAY_TPU_CLIENT_AUTHKEY or "
                "init a cluster first)")
        server = transfer.start_transfer_server(
            client, f"{ip}:0", [f"{ip}:0"])
        addr = server.address()
        self._authkey = authkey
        from ray_tpu.core.secure_transport import make_listener

        listener = make_listener((ip, 0), backlog=64)
        self._server = server
        self._xfer_addr = addr
        self._arm_listener = listener
        self._arm_addr = (ip, listener.address[1])
        threading.Thread(target=self._arm_loop, daemon=True,
                         name="rt-device-plane-arm").start()

    @property
    def available(self) -> bool:
        if not CONFIG.device_plane:
            return False
        self._ensure_started()
        return self._server is not None

    @property
    def disabled_reason(self) -> Optional[str]:
        return self._disabled_reason

    # -- producer side -----------------------------------------------------------

    def export(self, tree: Any) -> DeviceHandle:
        """Register a pytree of jax.Arrays for device-native fetch by peers.

        The plane holds strong references until `release(handle.key)` — exports
        pin device memory, so producers release as soon as consumers are done
        (P/D: when the decode side acks; channels: on next write).
        """
        if not self.available:
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        flat, treedef = jax.tree.flatten(tree)
        if not flat:
            raise DevicePlaneError("empty pytree")
        specs = tuple(
            ArraySpec(tuple(x.shape), str(x.dtype), _describe_sharding(x), x.nbytes)
            for x in flat
        )
        key = secrets.token_bytes(16)
        with self._lock:
            self._exports[key] = flat
            self.counters["exports"] += 1
        host, port = self._arm_addr
        return DeviceHandle(
            arm_host=host, arm_port=port, key=key, specs=specs,
            treedef_pickle=pickle.dumps(treedef),
            nbytes=sum(s.nbytes for s in specs))

    def release(self, key: bytes) -> None:
        with self._lock:
            self._exports.pop(key, None)

    def _arm_loop(self) -> None:
        while True:
            try:
                conn = self._arm_listener.accept()
            except EOFError:
                continue  # one bad/failed dial (TLS probe) must not stop serving
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_arm, args=(conn,), daemon=True,
                             name="rt-device-plane-serve").start()

    def _serve_arm(self, conn) -> None:
        from multiprocessing.connection import deliver_challenge, answer_challenge
        import pickle

        try:
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
            while True:
                op, key = pickle.loads(conn.recv_bytes())
                if op == "release":
                    self.release(key)
                    conn.send_bytes(pickle.dumps(("ok",)))
                    continue
                if op != "arm":
                    conn.send_bytes(pickle.dumps(("err", f"bad op {op!r}")))
                    continue
                with self._lock:
                    flat = self._exports.get(key)
                    if flat is None:
                        conn.send_bytes(pickle.dumps(("gone",)))
                        continue
                    self._uuid_counter += 1
                    uuid = self._uuid_counter
                    self.counters["arms"] += 1
                # await_pull holds buffer refs in the server until pulled.
                self._server.await_pull(uuid, flat)
                conn.send_bytes(pickle.dumps(("ok", self._xfer_addr, uuid)))
        except (EOFError, OSError, pickle.UnpicklingError):
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass

    # -- consumer side -----------------------------------------------------------

    def fetch(self, handle: DeviceHandle, release: bool = False) -> Any:
        """Pull an exported pytree device-to-device. Raises DevicePlaneError on any
        failure (producer gone, topology mismatch) — callers fall back to host.

        release=True acks the producer after a successful pull so it drops its
        pinned export immediately (single-consumer handoffs like P/D KV)."""
        if not self.available:
            self.counters["fallbacks"] += 1
            raise DevicePlaneError(self._disabled_reason or "device plane disabled")
        import jax
        import pickle

        try:
            xfer_addr, uuid = self._arm(handle)
            shardings = [_rebuild_sharding(s.sharding) for s in handle.specs]
            avals = [
                jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
                for s, sh in zip(handle.specs, shardings)
            ]
            conn = self._connection(xfer_addr)
            try:
                flat = conn.pull(uuid, avals)
            except Exception:
                # A failed pull poisons the PJRT connection: drop it so the next
                # fetch redials instead of inheriting a dead socket.
                with self._lock:
                    self._conns.pop(xfer_addr, None)
                raise
            with self._lock:
                self.counters["pulls"] += 1
                self.counters["bytes_pulled"] += handle.nbytes
            if release:
                try:
                    self._control(handle, ("release", handle.key))
                except Exception:
                    pass  # producer TTL-prunes as backstop
            treedef = pickle.loads(handle.treedef_pickle)
            return jax.tree.unflatten(treedef, flat)
        except DevicePlaneError:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise
        except Exception as e:
            with self._lock:
                self.counters["fallbacks"] += 1
            raise DevicePlaneError(f"device fetch failed: {type(e).__name__}: {e}") from e

    def _control(self, handle: DeviceHandle, msg: Tuple) -> Tuple:
        import pickle

        from ray_tpu.core.secure_transport import dial
        from ray_tpu.util.client.server import load_authkey

        authkey = self._authkey or load_authkey()
        if authkey is None:
            raise DevicePlaneError("no cluster session authkey")
        try:
            conn = dial((handle.arm_host, handle.arm_port), authkey=authkey)
        except Exception as e:
            raise DevicePlaneError(f"producer unreachable: {e}") from e
        try:
            conn.send_bytes(pickle.dumps(msg))
            return pickle.loads(conn.recv_bytes())
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _arm(self, handle: DeviceHandle) -> Tuple[str, int]:
        resp = self._control(handle, ("arm", handle.key))
        if resp[0] == "gone":
            raise DevicePlaneError("export was released by the producer")
        if resp[0] != "ok":
            raise DevicePlaneError(f"arm failed: {resp!r}")
        return resp[1], resp[2]

    def _connection(self, xfer_addr: str):
        with self._lock:
            conn = self._conns.get(xfer_addr)
        if conn is not None:
            return conn
        conn = self._server.connect(xfer_addr)
        with self._lock:
            self._conns[xfer_addr] = conn
        return conn

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["exports_live"] = len(self._exports)
        return out


_plane: Optional[DevicePlane] = None
_plane_lock = threading.Lock()


def plane() -> DevicePlane:
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = DevicePlane()
    return _plane
