"""TLS transport for the raw-socket planes (data plane, device-plane arm server).

multiprocessing.connection has no TLS story, so when RAY_TPU_USE_TLS is set the
listeners/dialers below replace it with ssl-wrapped sockets exposing the same
duck-typed surface the planes (and mp's deliver/answer_challenge) use:
send_bytes / recv_bytes / poll / fileno / close. Framing is a 4-byte big-endian
length prefix. Plaintext or wrong-CA peers fail the TLS handshake — refused
before a single protocol byte is exchanged (reference tls_utils.py RAY_USE_TLS
across src/ray/rpc and the object manager).
"""
from __future__ import annotations

import select
import socket
import struct
from typing import Optional, Tuple


class SecureConnection:
    """mp.Connection-compatible wrapper over a TLS-wrapped blocking socket.

    Server-side sockets arrive with the handshake PENDING (wrap_socket with
    do_handshake_on_connect=False): the accept loop must never block on a
    peer's handshake, so it completes lazily — bounded by _HANDSHAKE_TIMEOUT_S
    — on the per-connection thread's first operation. poll() before the
    handshake returns False immediately while no peer bytes have arrived; once
    they have, the first poll/recv may block up to the handshake timeout."""

    @property
    def _HANDSHAKE_TIMEOUT_S(self):  # CONFIG-backed (read at use)
        from ray_tpu.config import CONFIG

        return CONFIG.tls_handshake_timeout_s

    def __init__(self, sock, handshake_pending: bool = False):
        self._sock = sock
        self._handshake_pending = handshake_pending

    def _ensure_handshake(self) -> None:
        if not self._handshake_pending:
            return
        self._handshake_pending = False
        prev = self._sock.gettimeout()
        self._sock.settimeout(self._HANDSHAKE_TIMEOUT_S)
        try:
            self._sock.do_handshake()
        except Exception as e:
            raise EOFError(f"TLS handshake failed: {e}") from e
        finally:
            try:
                self._sock.settimeout(prev)
            except OSError:
                pass

    def send_bytes(self, buf) -> None:
        self._ensure_handshake()
        mv = buf if isinstance(buf, memoryview) else memoryview(buf)
        n = mv.nbytes
        if n > 16384:
            # large frames (data-plane chunks): header then the caller's buffer
            # directly — no staging copy of the payload
            self._sock.sendall(struct.pack("!I", n))
            self._sock.sendall(mv)
        else:
            self._sock.sendall(struct.pack("!I", n) + bytes(mv))

    # mp.Connection.send pickles; the planes only use send/recv for small
    # control tuples (the device-plane handle hop), so mirror that here.
    def send(self, obj) -> None:
        import pickle

        self.send_bytes(pickle.dumps(obj))

    def recv(self):
        import pickle

        return pickle.loads(self.recv_bytes())

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self._sock.recv(min(n, 1 << 20))
            if not chunk:
                raise EOFError("secure connection closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def recv_bytes(self, maxlength: Optional[int] = None) -> bytes:
        self._ensure_handshake()
        (size,) = struct.unpack("!I", self._recv_exact(4))
        if maxlength is not None and size > maxlength:
            raise OSError(f"message too large ({size} > {maxlength})")
        return self._recv_exact(size)

    def recv_bytes_into(self, buf, offset: int = 0) -> int:
        """mp.Connection-compatible recv-into: the next frame lands directly in
        `buf` (a writable buffer) at `offset` — the data plane uses this to
        stream chunks straight into a destination shm mapping with no
        intermediate bytes object."""
        self._ensure_handshake()
        (size,) = struct.unpack("!I", self._recv_exact(4))
        mv = buf if isinstance(buf, memoryview) else memoryview(buf)
        mv = mv[offset:]
        if size > mv.nbytes:
            raise BufferError(
                f"frame of {size} bytes exceeds buffer room ({mv.nbytes})")
        got = 0
        while got < size:
            n = self._sock.recv_into(mv[got:], min(size - got, 1 << 20))
            if n == 0:
                raise EOFError("secure connection closed")
            got += n
        return size

    def poll(self, timeout: float = 0.0) -> bool:
        # A pending server-side handshake must not break poll's timeout
        # contract for the COMMON stall (a peer that connected but sent
        # nothing): no bytes waiting -> return False without touching the
        # handshake (ADVICE r4: poll(0) used to block 15 s there). Once
        # handshake bytes HAVE arrived, the handshake runs with its full
        # timeout — shrinking it to the poll timeout would kill healthy
        # high-RTT peers mid-round-trip; this one case may still block up to
        # _HANDSHAKE_TIMEOUT_S (documented in the class docstring).
        if self._handshake_pending:
            r, _, _ = select.select([self._sock], [], [], timeout)
            if not r:
                return False
            self._ensure_handshake()
        # TLS may hold already-decrypted bytes in its buffer; select alone
        # would miss them
        if getattr(self._sock, "pending", lambda: 0)():
            return True
        r, _, _ = select.select([self._sock], [], [], timeout)
        return bool(r)

    def fileno(self) -> int:
        return self._sock.fileno()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class SecureListener:
    """mp.Listener-compatible mTLS listener: accept() completes the handshake
    and returns a SecureConnection; failed handshakes raise EOFError (matching
    mp.Listener's bad-dial behavior, which callers already tolerate)."""

    def __init__(self, address: Tuple[str, int], backlog: int = 64):
        from ray_tpu.core import tls_utils

        self._ctx = tls_utils.server_ssl_context()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(backlog)
        self.address = self._sock.getsockname()

    def accept(self) -> SecureConnection:
        import ssl

        conn, _ = self._sock.accept()
        try:
            # handshake deferred: a peer that never sends a ClientHello must
            # stall only its own connection thread, never the accept loop
            wrapped = self._ctx.wrap_socket(conn, server_side=True,
                                            do_handshake_on_connect=False)
        except (ssl.SSLError, OSError) as e:
            try:
                conn.close()
            except OSError:
                pass
            raise EOFError(f"TLS wrap failed: {e}") from e
        return SecureConnection(wrapped, handshake_pending=True)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def make_listener(address: Tuple[str, int], backlog: int = 64):
    """TLS listener when RAY_TPU_USE_TLS, else plain mp.connection.Listener."""
    from ray_tpu.core import tls_utils

    if tls_utils.use_tls():
        return SecureListener(address, backlog=backlog)
    from multiprocessing.connection import Listener

    return Listener(address, backlog=backlog)


def dial(address: Tuple[str, int], authkey: Optional[bytes] = None,
         timeout: Optional[float] = None):
    """TLS dial when RAY_TPU_USE_TLS, else plain mp.connection.Client. The
    mp challenge auth still runs over the encrypted channel when authkey is
    given — TLS authenticates the transport, the authkey scopes the cluster."""
    from ray_tpu.core import tls_utils

    if tls_utils.use_tls():
        from multiprocessing.connection import answer_challenge, deliver_challenge

        ctx = tls_utils.client_ssl_context()
        raw = socket.create_connection(address, timeout=timeout)
        try:
            raw.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        sock = ctx.wrap_socket(raw)
        sock.settimeout(None)  # planes manage stall bounds at the fd level
        conn = SecureConnection(sock)
        if authkey is not None:
            answer_challenge(conn, authkey)
            deliver_challenge(conn, authkey)
        return conn
    from multiprocessing.connection import Client, answer_challenge, deliver_challenge

    # authkey handled here, not by Client: the challenge must run AFTER
    # TCP_NODELAY is set, or its tiny request/response writes serialize on
    # Nagle + delayed-ACK (~40 ms per control round-trip on loopback)
    conn = Client(address)
    set_nodelay(conn.fileno())
    if authkey is not None:
        answer_challenge(conn, authkey)
        deliver_challenge(conn, authkey)
    return conn


def set_nodelay(fd: int) -> None:
    """TCP_NODELAY on a raw fd (mp.Connection hides its socket object)."""
    import os

    s = socket.socket(fileno=os.dup(fd))
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # non-TCP transport (unix socket test listeners)
    finally:
        s.close()
