"""ActorClass / ActorHandle / ActorMethod: the @ray_tpu.remote class API.

Capability parity: reference python/ray/actor.py (ActorClass:1111, ActorClass._remote:1402,
ActorMethod._remote:784, ActorHandle:1784). Method calls are dispatched FIFO to the actor's
pinned worker process (pipelined through its pipe, like the reference's sequential actor
submit queue, src/ray/core_worker/transport/sequential_actor_submit_queue.h).
"""
from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

import cloudpickle

from . import global_state
from .ids import ActorID, ObjectID, TaskID
from .task import build_resources, compute_fn_id, encode_args, register_function
from .task_spec import TaskSpec

_DEFAULT_ACTOR_OPTIONS = dict(
    num_cpus=1.0,
    num_tpus=0.0,
    resources=None,
    max_restarts=None,  # resolved from CONFIG.actor_max_restarts at decoration
    max_task_retries=0,
    name=None,
    namespace="",
    lifetime=None,  # None | "detached"
    scheduling_strategy="DEFAULT",
    runtime_env=None,
    max_concurrency=1,
    concurrency_groups=None,  # {"group": n_threads}; 0 = thread-per-call
)


def extract_method_meta(cls) -> Dict[str, Dict[str, Any]]:
    meta = {}
    for name, member in inspect.getmembers(cls, predicate=callable):
        if name.startswith("__") and name != "__call__":
            continue
        meta[name] = {
            "num_returns": getattr(member, "_num_returns", 1),
            "concurrency_group": getattr(member, "_concurrency_group", ""),
            # async def methods run interleaved on the actor's event loop
            # (reference python/ray/actor.py:2352 async actors)
            "is_async": inspect.iscoroutinefunction(member),
        }
    return meta


def method(*, num_returns: int = 1, concurrency_group: str = ""):
    """Decorator matching reference @ray.method(num_returns=..., concurrency_group=...)."""

    def deco(fn):
        fn._num_returns = num_returns
        fn._concurrency_group = concurrency_group
        return fn

    return deco


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, num_returns=self._num_returns)

    def options(self, num_returns: Optional[int] = None,
                concurrency_group: Optional[str] = None, **_ignored):
        m = ActorMethod(self._handle, self._name, num_returns or self._num_returns,
                        concurrency_group if concurrency_group is not None
                        else self._concurrency_group)
        return m

    def _remote(self, args, kwargs, num_returns=1):
        from ray_tpu.util.tracing import get_trace_context

        ctx = global_state.worker()
        meta, arg_refs, pins = encode_args(ctx, args, kwargs)
        streaming = num_returns == "streaming"
        n_rets = 1 if streaming else num_returns
        task_id = TaskID.generate()
        spec = TaskSpec(
            task_id=task_id,
            kind="actor_method",
            trace_ctx=get_trace_context(),
            fn_id=b"\x00" * 16,
            fn_bytes=None,
            name=f"{self._name}",
            args_meta=meta,
            arg_refs=arg_refs,
            num_returns=-1 if streaming else n_rets,
            return_ids=[ObjectID.generate() for _ in range(n_rets)],
            actor_id=self._handle._actor_id,
            method_name=self._name,
            concurrency_group=self._concurrency_group,
        )
        refs = ctx.submit(spec)
        del pins  # safe to release: submit() pinned the args
        if streaming:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0], task_id)
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference actor.py bind -> ray.dag)."""
        from ray_tpu.dag.compiled import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor method {self._name} must be invoked with .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_meta: Dict[str, Dict[str, Any]], owned: bool = False):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_method_meta", method_meta)
        object.__setattr__(self, "_owned", owned)

    def __del__(self):
        # Reference semantics: a non-detached actor dies when its original handle goes
        # out of scope (python/ray/actor.py handle GC). Serialized copies are borrows.
        # Queued, never direct: GC can run this on a thread holding runtime locks.
        if getattr(self, "_owned", False):
            try:
                from . import global_state

                if global_state.try_worker() is not None:
                    global_state.enqueue_gc_action("kill_actor", self._actor_id)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass

    def __getattr__(self, name: str):
        meta = object.__getattribute__(self, "_method_meta")
        if name in meta:
            return ActorMethod(self, name, meta[name].get("num_returns", 1),
                               meta[name].get("concurrency_group", ""))
        if name == "__ray_call__":
            # run an arbitrary fn(instance, *args) on the actor (reference actor.py)
            return ActorMethod(self, "__ray_call__", 1)
        if name.startswith("_"):
            raise AttributeError(name)
        # Unknown methods still get a handle (meta may be stale after code update).
        return ActorMethod(self, name, 1)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:16]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_meta))

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __hash__(self):
        return hash(self._actor_id)


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = {**_DEFAULT_ACTOR_OPTIONS, **options}
        if self._options.get("max_restarts") is None:
            from ray_tpu.config import CONFIG

            self._options["max_restarts"] = CONFIG.actor_max_restarts
        self._cls_bytes: Optional[bytes] = None
        self._cls_id: Optional[bytes] = None
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def options(self, **options) -> "ActorClass":
        ac = ActorClass(self._cls, **{**self._options, **options})
        ac._cls_bytes = self._cls_bytes
        ac._cls_id = self._cls_id
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        from ray_tpu.util.tracing import get_trace_context

        ctx = global_state.worker()
        if self._cls_bytes is None:
            self._cls_bytes = cloudpickle.dumps(self._cls)
            self._cls_id = compute_fn_id(self._cls_bytes)
        register_function(ctx, self._cls_id, self._cls_bytes)
        meta, arg_refs, pins = encode_args(ctx, args, kwargs)
        actor_id = ActorID.generate()
        method_meta = extract_method_meta(self._cls)
        declared = set((opts.get("concurrency_groups") or {}))
        for mname, m in method_meta.items():
            g = m.get("concurrency_group")
            if g and g not in declared:
                raise ValueError(
                    f"method {self.__name__}.{mname} uses concurrency group {g!r}, "
                    f"which is not declared in concurrency_groups ({sorted(declared)})")
        from ray_tpu.runtime_env import resolved_runtime_env

        runtime_env = resolved_runtime_env(opts.get("runtime_env"))
        spec = TaskSpec(
            task_id=TaskID.generate(),
            kind="actor_creation",
            fn_id=self._cls_id,
            fn_bytes=None,
            name=f"{self.__name__}.__init__",
            args_meta=meta,
            arg_refs=arg_refs,
            num_returns=1,
            return_ids=[ObjectID.generate()],
            resources=build_resources(opts),
            scheduling_strategy=opts["scheduling_strategy"],
            max_retries=0,
            actor_id=actor_id,
            max_restarts=opts["max_restarts"],
            actor_name=opts.get("name"),
            actor_namespace=opts.get("namespace") or "",
            runtime_env=runtime_env,
            method_meta=method_meta,
            detached=opts.get("lifetime") == "detached",
            max_concurrency=max(1, int(opts.get("max_concurrency") or 1)),
            concurrency_groups=dict(opts["concurrency_groups"])
            if opts.get("concurrency_groups") else None,
            trace_ctx=get_trace_context(),
        )
        ctx.submit(spec)
        del pins  # safe to release: submit() pinned the args
        return ActorHandle(actor_id, method_meta, owned=True)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )
