"""Serialization with zero-copy out-of-band buffers.

Capability parity: reference python/ray/_private/serialization.py + vendored cloudpickle.
Uses pickle protocol 5: large contiguous buffers (numpy arrays, jax host arrays, bytes)
are extracted out-of-band so they can be placed in shared memory and mapped zero-copy by
readers instead of being copied through the pickle stream.
"""
from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import Any, List, Sequence

import cloudpickle

# Buffers smaller than this stay inline in the pickle stream (header overhead not worth it).
from ray_tpu.config import memoized_flag

# per-serialize fast path: memoized against the raw env string
_oob_threshold = memoized_flag("oob_threshold_bytes")


@dataclass
class SerializedObject:
    """A pickled object split into metadata stream + raw out-of-band buffers."""

    meta: bytes
    buffers: List[pickle.PickleBuffer]

    @property
    def total_bytes(self) -> int:
        return len(self.meta) + sum(b.raw().nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten into one contiguous frame: [n][meta_len][meta][buf_len buf]*.

        Preallocates the exact frame and fills it with write_into — no BytesIO
        grow-and-copy churn. Large puts never even come here: materialize()
        calls write_into straight on the arena/segment mapping (one copy
        total); this covers inline-threshold frames and dumps()."""
        out = bytearray(self.frame_bytes)
        self.write_into(memoryview(out))
        return bytes(out)

    def write_into(self, mv: memoryview) -> None:
        """Write the flattened frame into a preallocated buffer (e.g. shared memory)."""
        off = 0
        nbufs = len(self.buffers)
        mv[off : off + 4] = nbufs.to_bytes(4, "little")
        off += 4
        mv[off : off + 8] = len(self.meta).to_bytes(8, "little")
        off += 8
        mv[off : off + len(self.meta)] = self.meta
        off += len(self.meta)
        for b in self.buffers:
            raw = b.raw().cast("B")
            mv[off : off + 8] = raw.nbytes.to_bytes(8, "little")
            off += 8
            mv[off : off + raw.nbytes] = raw
            off += raw.nbytes

    @property
    def frame_bytes(self) -> int:
        return 4 + 8 + len(self.meta) + sum(8 + b.raw().nbytes for b in self.buffers)


def serialize(obj: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def callback(buf: pickle.PickleBuffer) -> bool:
        if buf.raw().nbytes >= _oob_threshold():
            buffers.append(buf)
            return False  # out-of-band
        return True  # keep inline

    meta = cloudpickle.dumps(obj, protocol=5, buffer_callback=callback)
    return SerializedObject(meta=meta, buffers=buffers)


def deserialize(meta: bytes, buffers: Sequence[Any]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def deserialize_frame(mv: memoryview) -> Any:
    """Inverse of SerializedObject.to_bytes/write_into. Buffers are zero-copy views of mv."""
    off = 0
    nbufs = int.from_bytes(mv[off : off + 4], "little")
    off += 4
    meta_len = int.from_bytes(mv[off : off + 8], "little")
    off += 8
    meta = bytes(mv[off : off + meta_len])
    off += meta_len
    buffers = []
    for _ in range(nbufs):
        blen = int.from_bytes(mv[off : off + 8], "little")
        off += 8
        buffers.append(mv[off : off + blen])
        off += blen
    return deserialize(meta, buffers)


def dumps(obj: Any) -> bytes:
    return serialize(obj).to_bytes()


def loads(data: bytes) -> Any:
    return deserialize_frame(memoryview(data))
