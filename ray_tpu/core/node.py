"""Node service: worker pool, router, scheduler, actor manager, driver context.

Capability parity: reference raylet (src/ray/raylet/node_manager.h:124 — worker leases,
dependency management, dispatch) + GCS actor manager (gcs_actor_manager.h:333) + the
cluster task manager scheduling policies (scheduling/cluster_task_manager.h:44). The
round-1 deployment runs the node service inside the driver process with spawned worker
processes; the same Cluster object models multiple virtual nodes (reference analog:
ray.cluster_utils.Cluster multi-raylet fixture) so multi-node scheduling semantics are
testable on one host.
"""
from __future__ import annotations

import atexit
import copy
import itertools
import multiprocessing
import multiprocessing.connection
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from . import global_state, object_store
from .exceptions import (
    ActorDiedError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .gcs import GCS, NodeInfo
from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .object_ref import ObjectRef
from .object_store import ObjectStore
from .placement_group import PlacementGroup, PlacementGroupManager
from .resources import ResourceLedger
from .task_spec import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    TaskSpec,
)

_mp = multiprocessing.get_context("spawn")

from ray_tpu.config import CONFIG


def _default_max_workers() -> int:
    return CONFIG.max_workers_per_node  # read at use: env changes apply live
def _worker_start_timeout() -> float:
    """Read at use: env changes apply live (config.py contract)."""
    from ray_tpu.config import CONFIG

    return CONFIG.worker_start_timeout_s


def _system_memory_fraction() -> Optional[float]:
    """Used-memory fraction from /proc/meminfo (reference MemoryMonitor reads
    cgroup/system usage the same way). None if unreadable."""
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    info[parts[0].rstrip(":")] = int(parts[1])
        total = info.get("MemTotal")
        avail = info.get("MemAvailable")
        if not total or avail is None:
            return None
        return 1.0 - avail / total
    except OSError:
        return None


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, process, conn, node: "NodeRuntime",
                 accel: str, pool_key: Optional[str] = None):
        self.worker_id = worker_id
        self.process = process
        self.conn = conn
        self.node = node
        self.accel = accel
        # idle-pool bucket: accel, or accel + runtime-env hash for workers
        # SPAWNED with task-specific env vars (reference: dedicated workers per
        # runtime env) — they may only be reused by tasks with the same env
        self.pool_key = pool_key or accel
        self.state = "starting"  # starting | idle | busy | blocked | dead
        self.started_at = time.time()  # start-timeout watchdog reference point
        self.known_fns: set = set()
        self.inflight: deque = deque()  # TaskSpecs sent, results pending (FIFO)
        self.resources_held: Dict[str, float] = {}
        self.bundle_ledger: Optional[ResourceLedger] = None
        self.actor_id: Optional[ActorID] = None
        self._send_lock = threading.Lock()
        self.blocked_reqs: set = set()

    def send(self, msg) -> None:
        with self._send_lock:
            self.conn.send_bytes(cloudpickle.dumps(msg))

    def alive(self) -> bool:
        return self.state != "dead" and self.process.is_alive()


class NodeRuntime:
    def __init__(self, cluster: "Cluster", node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None, max_workers: Optional[int] = None):
        self.cluster = cluster
        self.node_id = node_id
        self.ledger = ResourceLedger(resources)
        self.labels = labels or {}
        self.max_workers = (max_workers if max_workers is not None
                            else _default_max_workers())
        self.idle: Dict[str, List[WorkerHandle]] = {}
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.alive = True
        # which host this node's workers (and their object storage) live on:
        # "local" = the head process's host; remote nodes use their agent's key
        self.host_key = "local"

    def num_workers(self) -> int:
        return len(self.workers)

    def pop_idle(self, pool_key: str) -> Optional[WorkerHandle]:
        pool = self.idle.get(pool_key)
        while pool:
            w = pool.pop()
            if w.alive():
                return w
            # same reap as steal_idle_slot: a dead idle worker not yet seen by
            # the router still counts toward max_workers — free its slot now
            # so the caller's spawn_worker doesn't hit the cap for nothing
            # (no-op if the death was already processed)
            self.cluster._on_worker_death(w)
        return None

    def push_idle(self, w: WorkerHandle) -> None:
        w.state = "idle"
        self.idle.setdefault(w.pool_key, []).append(w)

    def steal_idle_slot(self, exclude_key: str) -> Optional[WorkerHandle]:
        """Pop one alive idle worker from a DIFFERENT pool so its slot can be
        re-used for a new pool key (reference: raylet WorkerPool idle-worker
        eviction). Without this, a node whose worker cap is filled by idle
        env-pinned workers can never admit a task with a new runtime env — the
        task queues forever. Env-keyed pools are evicted first (they are
        per-job specials; plain pools are the shared fast path)."""
        for key in sorted(self.idle, key=lambda k: ("|env:" not in k, k)):
            if key == exclude_key:
                continue
            pool = self.idle[key]
            while pool:
                w = pool.pop()
                if w.alive():
                    return w
                # A dead idle worker still holds a node.workers entry, so it
                # counts toward max_workers and the post-eviction spawn retry
                # would hit the cap again — reap it through the normal death
                # path so the slot is actually freed.
                self.cluster._on_worker_death(w)
        return None

    def spawn_worker(self, accel: str, extra_env: Optional[Dict[str, str]] = None,
                     pool_key: Optional[str] = None,
                     container: Optional[Dict] = None) -> Optional[WorkerHandle]:
        if len(self.workers) >= self.max_workers:
            return None
        if container is not None:
            return self._spawn_container_worker(accel, container, extra_env,
                                                pool_key)
        from .worker import worker_main

        worker_id = WorkerID.generate()
        parent_conn, child_conn = _mp.Pipe(duplex=True)
        env = dict(self.cluster.worker_env)
        if extra_env:
            # runtime_env env_vars present at process SPAWN: process-level vars
            # (XLA_FLAGS, JAX_PLATFORMS, ...) must exist before first import
            env.update(extra_env)
        proc = _mp.Process(
            target=worker_main,
            args=(child_conn, self.node_id.hex(), worker_id.hex(), accel, env),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        w = WorkerHandle(worker_id, proc, parent_conn, self, accel,
                         pool_key=pool_key)
        self.workers[worker_id] = w
        self.cluster._register_conn(w)
        return w

    def _spawn_container_worker(self, accel: str, container: Dict,
                                extra_env: Optional[Dict[str, str]],
                                pool_key: Optional[str]) -> WorkerHandle:
        """Launch a worker INSIDE a container image (runtime_env container/
        image_uri — reference _private/runtime_env/image_uri.py): the node
        listens on an authkey'd loopback socket, the container dials back, and
        from then on the worker is indistinguishable from a pipe worker.
        Dispatches sent before the dial-back buffer in a PendingConn; the
        handle joins the cluster recv loop at attach. A container that never
        dials back goes through the normal worker-death bookkeeping (task
        retried/failed, slot freed)."""
        from . import container as _ctr

        worker_id = WorkerID.generate()
        env = dict(self.cluster.worker_env)
        if extra_env:
            env.update(extra_env)
        handle_ready = threading.Event()
        holder: Dict[str, WorkerHandle] = {}

        def on_attach(conn) -> None:
            handle_ready.wait(timeout=30)
            w = holder["w"]
            with w._send_lock:
                w.conn.attach(conn)
                w.conn = conn
            self.cluster._register_conn(w)

        def on_fail(err) -> None:
            handle_ready.wait(timeout=30)
            self.cluster._on_worker_death(holder["w"], _ctr.ContainerRuntimeError(
                f"container worker never dialed back: {err}"))

        proc = _ctr.spawn_with_dialback(
            container, self.node_id.hex(), worker_id.hex(), accel, env,
            on_attach, on_fail, timeout_s=_worker_start_timeout())
        w = WorkerHandle(worker_id, proc, _ctr.PendingConn(), self, accel,
                         pool_key=pool_key)
        holder["w"] = w
        handle_ready.set()
        self.workers[worker_id] = w
        return w


class _RemoteProc:
    """Stand-in for a remote worker's Process handle: liveness is what the agent
    reports; terminate() asks the agent to kill the OS process."""

    def __init__(self, agent: "AgentHandle", wid_hex: str):
        self._agent = agent
        self._wid_hex = wid_hex
        self.dead = False
        # the OS pid lives on the agent's host; state.list_workers() (and
        # anything else duck-typing Process) reads .pid, so carry an honest
        # "unknown here" instead of AttributeError-ing the whole status call
        self.pid = None

    def is_alive(self) -> bool:
        return not self.dead and self._agent.alive

    def terminate(self) -> None:
        self.dead = True
        try:
            self._agent.send(("kill_worker", self._wid_hex))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    kill = terminate

    def join(self, timeout: Optional[float] = None) -> None:
        pass  # the agent reaps its own children


class RemoteWorkerHandle(WorkerHandle):
    """A worker process living on a remote host, reached through its node agent.

    Same state machine as WorkerHandle; send() relays the already-pickled worker
    message through the agent's TCP connection (reference analog: CoreWorker
    task push over gRPC to a worker on another node)."""

    def __init__(self, worker_id: WorkerID, agent: "AgentHandle",
                 node: "NodeRuntime", accel: str):
        super().__init__(worker_id, _RemoteProc(agent, worker_id.hex()), None, node, accel)
        self.agent = agent

    def send(self, msg) -> None:
        # the agent handle's own lock serializes the socket write
        self.agent.send(("to_worker", self.worker_id.hex(), cloudpickle.dumps(msg)))


class AgentHandle:
    """Head-side view of one connected node agent (reference: a registered
    raylet in GcsNodeManager, gcs_node_manager.h:49)."""

    def __init__(self, cluster: "Cluster", conn, node: "NodeRuntime"):
        self.cluster = cluster
        self.conn = conn
        self.node = node
        self.host_key = node.node_id.hex()
        self.alive = True
        self.last_heartbeat = time.time()
        # (ip, port) of the agent's DataServer; None = old agent, relay only
        self.data_addr: Optional[Tuple[str, int]] = None
        self.workers: Dict[str, RemoteWorkerHandle] = {}  # wid_hex -> handle
        self._req_counter = itertools.count()
        self._pending: Dict[int, list] = {}  # req_id -> [Event, ok, value]
        self._pending_lock = threading.Lock()

    def send(self, msg) -> None:
        if not self.alive:
            raise OSError(f"node agent {self.host_key[:8]} is dead")
        # typed gRPC stream: tuples encode to protobuf at the transport
        # boundary (agent_rpc.encode_head_msg); no pickle on agent control
        self.conn.send(msg)

    def call(self, op: str, *args, timeout: float = 60.0):
        """Blocking RPC to the agent (object fetch/store); replies are matched
        by the router thread — never call from the router thread itself."""
        req_id = next(self._req_counter)
        slot = [threading.Event(), False, None]
        with self._pending_lock:
            if not self.alive:
                raise OSError(f"node agent {self.host_key[:8]} is dead")
            self._pending[req_id] = slot
        try:
            self.send(("req", req_id, op, args))
        except Exception:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise
        if not slot[0].wait(timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"agent {self.host_key[:8]} {op} timed out")
        if not slot[1]:
            raise slot[2]
        return slot[2]

    def on_reply(self, req_id: int, ok: bool, value) -> None:
        with self._pending_lock:
            slot = self._pending.pop(req_id, None)
        if slot is not None:
            slot[1], slot[2] = ok, value
            slot[0].set()

    def fail_all_pending(self, reason: str) -> None:
        with self._pending_lock:
            self.alive = False
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot[1], slot[2] = False, OSError(reason)
            slot[0].set()


class RemoteNodeRuntime(NodeRuntime):
    """A node whose worker pool lives on another host, managed by its agent."""

    def __init__(self, cluster: "Cluster", node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]], max_workers: int):
        super().__init__(cluster, node_id, resources, labels, max_workers)
        self.agent: Optional[AgentHandle] = None  # set right after construction
        self.host_key = node_id.hex()

    def spawn_worker(self, accel: str, extra_env: Optional[Dict[str, str]] = None,
                     pool_key: Optional[str] = None,
                     container: Optional[Dict] = None) -> Optional[WorkerHandle]:
        if len(self.workers) >= self.max_workers or not self.agent.alive:
            return None
        worker_id = WorkerID.generate()
        w = RemoteWorkerHandle(worker_id, self.agent, self, accel)
        if pool_key:
            w.pool_key = pool_key
        try:
            self.agent.send(("spawn_worker", worker_id.hex(), accel,
                             dict(extra_env or {}), container))
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return None) by design
        except Exception:
            return None
        self.workers[worker_id] = w
        self.agent.workers[worker_id.hex()] = w
        return w


class ActorState:
    def __init__(self, actor_id: ActorID, creation_spec: TaskSpec, method_meta: Dict[str, Any]):
        self.actor_id = actor_id
        self.creation_spec = creation_spec
        self.method_meta = method_meta
        self.state = "pending"  # pending | alive | restarting | dead
        self.worker: Optional[WorkerHandle] = None
        self.restarts_used = 0
        self.death_cause: Optional[Exception] = None
        self.name: Optional[str] = creation_spec.actor_name
        self.namespace: str = creation_spec.actor_namespace
        self.detached = creation_spec.detached
        self.handle_count = 0
        self.kill_on_creation = False


class TaskState:
    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.worker: Optional[WorkerHandle] = None
        self.resources_node: Optional[NodeRuntime] = None
        self.resources: Dict[str, float] = {}
        self.bundle_ledger: Optional[ResourceLedger] = None
        self.cancelled = False
        # timeline events (reference: GcsTaskManager task events / ray.timeline)
        self.submitted_at: float = time.time()
        self.dispatched_at: Optional[float] = None


class Cluster:
    """The whole single-host deployment: GCS + object store + N virtual nodes + router."""

    def __init__(self, resources: Dict[str, float], worker_env: Optional[Dict[str, str]] = None,
                 max_workers_per_node: Optional[int] = None,
                 object_store_memory: Optional[int] = None):
        self.gcs = GCS()
        self.store = ObjectStore()
        self.pg_manager = PlacementGroupManager()
        self.worker_env = worker_env or {}
        # job-level default runtime env (ray.init(runtime_env=...)): merged
        # under per-call envs at submission, pre-warmed by agents on join
        self.default_runtime_env: Optional[Dict[str, Any]] = None
        # Node-wide C++ shared-memory arena for large objects (plasma equivalent).
        # Workers attach via the env var; falls back to per-object segments if the
        # native build or shm creation fails.
        if object_store_memory is None:
            object_store_memory = CONFIG.object_store_bytes
        self.arena_name = (
            object_store.init_arena(object_store_memory) if object_store_memory > 0 else None
        )
        if self.arena_name:
            self.worker_env.setdefault(object_store._ARENA_ENV, self.arena_name)
        self.fn_table: Dict[bytes, bytes] = {}
        # restart-as-a-non-event: reload the function/class table journaled by
        # _register_fn. Workers and clients dedup their register_fn sends per
        # head LIFETIME, so nothing re-ships the bytes to a restarted head —
        # without this reload, every post-restart actor (re)start dies with
        # "unknown function".
        for _fn_key in self.gcs.kv.keys(namespace="@fns"):
            _fn_val = self.gcs.kv.get(_fn_key, namespace="@fns")
            if _fn_val is not None:
                self.fn_table[bytes(_fn_key)] = _fn_val
        self.metrics_by_worker: Dict[Any, list] = {}
        # per-NODE pre-aggregated deltas (PR 17): upgraded agents merge their
        # workers' pushes locally and ship one snapshot per flush tick —
        # entries here REPLACE that agent's per-worker entries above, so the
        # head-side merge stays O(nodes). Un-upgraded agents keep relaying
        # per-worker frames and land in metrics_by_worker (automatic fallback).
        self.metrics_by_node: Dict[str, list] = {}
        # control-RPC inlet accounting for backpressure: frames seen since
        # the last scrape tick, evaluated by _evaluate_inlet_backpressure
        self._inlet_lock = threading.Lock()
        self._inlet_frames = 0
        self._bp_level = 0
        self.task_events: deque = deque(maxlen=10000)
        self.trace_spans: deque = deque(maxlen=10000)
        # merged hot-path telemetry events (util/telemetry.py): worker batches
        # arrive clock-aligned (ts_ns += the batch's measured head-clock
        # offset) and proc-tagged, so readers get ONE comparable timeline
        self.telemetry_events: deque = deque(maxlen=50000)
        self.actors: Dict[ActorID, ActorState] = {}
        self.tasks: Dict[TaskID, TaskState] = {}
        self.pending: deque = deque()  # TaskSpecs waiting for dispatch
        # waiting-task count per placement shape: lets submit() try an immediate
        # dispatch ONLY when no same-shape task is queued ahead (per-shape FIFO —
        # actor-method call order depends on it), and lets the dispatch pass stop
        # as soon as every waiting shape is known blocked
        self._pending_shape_counts: Dict[Any, int] = {}
        self.pending_pgs: List[PlacementGroup] = []
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, NodeRuntime] = {}
        self._node_order: List[NodeID] = []
        self._spread_counter = itertools.count()
        self._conns: Dict[Any, WorkerHandle] = {}
        self._wakeup_r, self._wakeup_w = _mp.Pipe(duplex=False)
        self._shutdown = False
        # multi-host plane (reference: GcsNodeManager + ObjectManager):
        self._agent_conns: Dict[Any, AgentHandle] = {}   # agent TCP conn -> handle
        self._agents_by_key: Dict[str, AgentHandle] = {}  # node_id hex -> handle
        # head-boot stamp: the agent reaper grants RAY_TPU_HEAD_RESTART_GRACE_S
        # after (re)start so nodes that were healthy through a head outage are
        # never reaped before they finish reattaching (ISSUE: restart is a
        # non-event, not a mass node-death event)
        self._boot_at = time.time()
        # (node_hex, oid) pairs whose reattach pin (store.incref) was already
        # taken: journal/reregister replay applied twice must be a no-op, not
        # a second pin that leaks the object forever
        self._reattach_pins: set = set()
        self._node_listener = None
        self.node_server_port: Optional[int] = None
        self._data_server = None   # head-side data plane (started with the
        self._data_client = None   # node server; data_plane.DataServer/Client)
        # cross-host replica directory: (oid, host_key) -> local (unwrapped) loc
        self._replicas: Dict[Tuple[ObjectID, str], Tuple] = {}
        self._transfers: Dict[Tuple[ObjectID, str], threading.Event] = {}
        self._transfer_lock = threading.Lock()
        self._localizing: set = set()  # (task_id, host) with an in-flight arg pull
        self._dispatch_blocked_on_args = False  # set by _try_dispatch (under _lock)
        self._pull_failures: Dict[TaskID, int] = {}  # consecutive arg-pull failures
        # streaming generator bookkeeping: items produced so far per task, and
        # the cutoff index past which an abandoned stream's items are dropped
        self._stream_counts: Dict[TaskID, int] = {}
        self._stream_abandoned: Dict[TaskID, int] = {}
        self._stream_cancel_sent: set = set()  # producers already told to stop
        # remote worker log rings: wid_hex -> {"node", "lines": deque[(stream, line)]}
        self._worker_logs: Dict[str, Dict[str, Any]] = {}
        self._worker_logs_lock = threading.Lock()
        # collective-group liveness registry (reference: the GCS knowing which
        # node holds each NCCL rank): group -> {rank: (WorkerHandle, epoch)},
        # fed by workers' collective_join/leave notes. Worker death looks up
        # the dead worker's ranks here and poisons each group's coordinator,
        # so survivors abort within one poll interval instead of burning the
        # full collective op timeout.
        self._collective_members: Dict[str, Dict[int, Tuple[WorkerHandle, int]]] = {}
        self._stream_completion: Dict[ObjectID, TaskID] = {}  # completion oid -> task
        # lineage for reconstruction: return oid -> creating TaskSpec while the
        # object is in scope and the task is retryable (reference
        # object_recovery_manager.h:43 + task_manager lineage pinning)
        self.lineage: Dict[ObjectID, TaskSpec] = {}
        self._recovering: set = set()  # oids with an in-flight reconstruction
        self._stack_dumps: Dict[str, Dict[str, str]] = {}  # token -> worker -> text
        self.store.on_free = self._on_object_freed
        self.store.on_spill = self._on_object_spilled
        self._object_store_capacity = object_store_memory
        self.spill_dir = os.path.join(
            CONFIG.spill_dir,
            f"ray_tpu_spill_{os.getpid()}_{os.urandom(2).hex()}")
        # spill watermarks (reference: object_spilling_threshold / local_object_manager)
        self.spill_threshold = CONFIG.spill_threshold
        self.spill_target = CONFIG.spill_target
        # memory monitor (reference memory_monitor.h:52 + worker_killing_policy)
        self.memory_usage_threshold = CONFIG.memory_usage_threshold
        self.memory_monitor_refresh_ms = CONFIG.memory_monitor_refresh_ms
        self._memory_sampler = _system_memory_fraction  # test seam
        self.num_oom_kills = 0
        self.store.on_remote_free = self._on_remote_free
        self._router_thread = threading.Thread(target=self._router, daemon=True, name="rt-router")
        self.head_node = self.add_node(resources, max_workers=max_workers_per_node)
        self._router_thread.start()
        self._maint_wakeup = threading.Event()
        from ray_tpu.util.logutil import LogThrottle

        self._maint_warn = LogThrottle(30.0)
        self._maint_thread = threading.Thread(
            target=self._maintenance_loop, daemon=True, name="rt-maintenance")
        self._maint_thread.start()
        # metrics history + SLO engine (util/metrics_history.py, util/slo.py):
        # the head samples the merged cross-worker snapshot into a bounded
        # frame ring every CONFIG.metrics_scrape_interval_s, then re-evaluates
        # the registered SLOs — the windowed-signal layer behind
        # state.metrics_history()/slo_status(), /api/history, /api/slo and
        # `ray-tpu status --watch`
        from ray_tpu.util.metrics_history import MetricsHistory, scraper_loop
        from ray_tpu.util.slo import SLOEngine

        self.metrics_history = MetricsHistory()
        self._restore_history_journal()
        self.slo_engine = SLOEngine(self.metrics_history)
        self._scraper_thread = threading.Thread(
            target=scraper_loop, daemon=True, name="rt-metrics-scraper",
            args=(self.metrics_history, self._scrape_merged_metrics,
                  lambda: self._shutdown, self._on_scrape_frame))
        self._scraper_thread.start()

    def _scrape_merged_metrics(self) -> Dict[str, Any]:
        """One merged cross-worker snapshot for the history scraper: the
        head's own registry + every worker's latest push + every node's
        pre-aggregated delta (the same merge state.get_metrics serves,
        reachable without the state-API guard)."""
        from ray_tpu.util import metrics as _m

        snaps = [_m._registry.snapshot()]
        snaps.extend(list(self.metrics_by_worker.values()))
        snaps.extend(list(self.metrics_by_node.values()))
        return _m.merge_snapshots(snaps)

    def _on_scrape_frame(self) -> None:
        """Per-scrape-tick control work, invoked by the scraper right after
        each frame lands: SLO evaluation, the inlet backpressure controller,
        and the history journal (head-restart durability)."""
        self.slo_engine.evaluate()
        self._evaluate_inlet_backpressure()
        self._journal_history()

    # -- control-plane: inlet accounting + backpressure --------------------------------

    def _note_inlet_frame(self) -> int:
        """Count one metrics/telemetry frame into the current scrape window;
        returns the running count so callers can shed past the hard ceiling."""
        with self._inlet_lock:
            self._inlet_frames += 1
            return self._inlet_frames

    def _inlet_shed_ceiling(self) -> int:
        """Hard per-window ceiling past which telemetry payloads are shed
        (visibly): 4x the backpressure bound. 0 = never shed."""
        bound = CONFIG.control_inlet_bound
        return bound * 4 if bound > 0 else 0

    def _evaluate_inlet_backpressure(self) -> None:
        """Escalate/clear the typed backpressure signal from the inlet frame
        count of the scrape window just ended: above the bound agents are
        told to widen their flush interval (doubling per level, capped at
        control_backpressure_max_s); below half the bound the level steps
        back down. Every transition is a counter bump + telemetry event —
        degradation is never silent."""
        from ray_tpu.util import telemetry as _tel

        with self._inlet_lock:
            frames = self._inlet_frames
            self._inlet_frames = 0
        _tel.get_gauge(
            "control_inlet_frames",
            "metrics/telemetry frames that reached the head's control inlet "
            "during the last scrape window").set(float(frames))
        bound = CONFIG.control_inlet_bound
        level = self._bp_level
        if bound <= 0:
            level = 0
        elif frames > bound:
            level += 1
        elif frames < bound // 2 and level > 0:
            level -= 1
        base = max(0.1, CONFIG.control_node_flush_s)
        cap = max(base, CONFIG.control_backpressure_max_s)
        min_interval = min(base * (2 ** level), cap) if level > 0 else 0.0
        if level == self._bp_level:
            return
        self._bp_level = level
        _tel.get_gauge(
            "control_backpressure_level",
            "current control-inlet backpressure level (0 = none)"
        ).set(float(level))
        _tel.get_counter(
            "control_backpressure_transitions_total",
            "control-inlet backpressure level changes", tag_keys=("dir",)
        ).inc(tags={"dir": "up" if frames > bound else "down"})
        if _tel.enabled():
            _tel.event("control.backpressure", cat="control", level=level,
                       inlet_frames=frames, min_interval_s=min_interval)
        with self._lock:
            agents = list(self._agent_conns.values())
        for a in agents:
            try:
                a.send(("control_backpressure", level, min_interval))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass

    # -- control-plane: history journal (head-restart durability) ----------------------

    _HISTORY_JOURNAL_KEY = b"frames"
    _HISTORY_JOURNAL_NS = "@metrics_history"

    def _journal_history(self) -> None:
        """Persist the last N scrape frames through the GCS KV path so SLO
        burn windows and the router's windowed-TTFT latency views survive a
        head restart (extends PR 15's re-derive discipline: what cannot be
        re-derived from live agents is journaled)."""
        n = CONFIG.control_history_journal_frames
        if n <= 0:
            return
        frames = self.metrics_history.frames()[-n:]
        if not frames:
            return
        try:
            self.gcs.kv.put(self._HISTORY_JOURNAL_KEY,
                            cloudpickle.dumps(frames),
                            namespace=self._HISTORY_JOURNAL_NS)
        # graftlint: allow[swallowed-exception] journal write is best-effort; only head-restart warm-start is lost
        except Exception:
            pass

    def _restore_history_journal(self) -> None:
        if CONFIG.control_history_journal_frames <= 0:
            return
        try:
            raw = self.gcs.kv.get(self._HISTORY_JOURNAL_KEY,
                                  namespace=self._HISTORY_JOURNAL_NS)
            if not raw:
                return
            restored = self.metrics_history.restore(cloudpickle.loads(raw))
            if restored:
                import logging as _logging

                _logging.getLogger("ray_tpu.node").info(
                    "restored %d metrics-history frames from the journal "
                    "(SLO windows warm-start)", restored)
        # graftlint: allow[swallowed-exception] a corrupt journal must not block head start; history simply starts cold
        except Exception:
            pass

    # -- topology --------------------------------------------------------------------
    def add_node(self, resources: Dict[str, float], labels: Optional[Dict[str, str]] = None,
                 max_workers: Optional[int] = None) -> NodeRuntime:
        node_id = NodeID.generate()
        node = NodeRuntime(self, node_id, resources, labels, max_workers)
        with self._lock:
            self._nodes[node_id] = node
            self._node_order.append(node_id)
        self.gcs.register_node(NodeInfo(node_id=node_id, resources=dict(resources), labels=labels or {}))
        self._schedule()
        return node

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.alive = False
            workers = list(node.workers.values())
        for w in workers:
            self._kill_worker(w, WorkerCrashedError(f"node {node_id.hex()[:8]} removed"))
        self.gcs.remove_node(node_id)

    def get_node_runtime(self, node_id: NodeID) -> Optional[NodeRuntime]:
        with self._lock:
            return self._nodes.get(node_id)

    def nodes(self) -> List[NodeRuntime]:
        with self._lock:
            return [self._nodes[nid] for nid in self._node_order if self._nodes[nid].alive]

    # -- multi-host: node server + agents ----------------------------------------------
    def start_node_server(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Accept node agents over the TYPED gRPC control plane (reference: GCS
        server accepting raylet registrations over gRPC, gcs_node_manager.h:49
        + src/ray/rpc/). Returns the bound port. Auth: the per-cluster session
        authkey rides the stream metadata; the head never unpickles agent
        control traffic."""
        from ray_tpu.util.client.server import generate_authkey, load_authkey

        if self._node_listener is not None:
            return self.node_server_port
        authkey = load_authkey() or generate_authkey()
        from . import agent_rpc

        self._node_listener = agent_rpc.AgentRpcServer(
            host, port, authkey, self._on_agent_stream)
        self.node_server_port = self._node_listener.port
        # the head's own data plane: agents pull head-resident objects (and the
        # head pulls agent-resident ones) chunked, off the control channel
        from . import data_plane

        if self._data_server is None:
            # read_pinned_any: chunk frames stream straight from the shm/arena
            # mapping (pinned against spill/free) — no per-pull copy on the head
            self._data_server = data_plane.DataServer(
                authkey, object_store.read_pinned_any)
            self._data_client = data_plane.DataClient(authkey)
        return self.node_server_port

    def _on_agent_stream(self, stream, first: Tuple) -> bool:
        """A fresh agent stream's first message: register or reregister."""
        try:
            if first[0] == "register":
                return self._register_agent(stream, first)
            if first[0] == "reregister":
                return self._reattach_agent(stream, first)
        except Exception:
            import traceback

            traceback.print_exc()
        return False

    def _register_agent(self, stream, msg) -> bool:
        _, resources, labels, max_workers, extras = msg
        node_id = NodeID.generate()
        node = RemoteNodeRuntime(self, node_id, resources, labels, max_workers)
        agent = AgentHandle(self, stream, node)
        node.agent = agent
        data_port = (extras or {}).get("data_port")
        if data_port and stream.peer_ip is not None:
            agent.data_addr = (stream.peer_ip, int(data_port))
        stream.on_message = lambda m: self._handle_agent_message(agent, m)
        stream.on_disconnect = lambda: self._on_agent_death(agent)
        try:
            stream.send_welcome({
                "node_id": node_id.hex(),
                "worker_env": dict(self.worker_env),
                "object_store_memory": self._object_store_capacity,
                "default_runtime_env": self.default_runtime_env,
            })
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:
            return False
        with self._lock:
            self._nodes[node_id] = node
            self._node_order.append(node_id)
            self._agent_conns[stream] = agent
            self._agents_by_key[agent.host_key] = agent
        self.gcs.register_node(NodeInfo(node_id=node_id, resources=dict(resources),
                                        labels={**(labels or {}), "agent": "remote"}))
        self._schedule()
        return True

    def _on_worker_log(self, agent: AgentHandle, wid_hex: str, stream: str,
                       text: str) -> None:
        """A remote worker's stdout/stderr lines: re-print on the driver with a
        (worker, host) prefix and keep a bounded ring for the state API
        (reference log_monitor.py:105 + `ray logs`)."""
        import sys as _sys

        lines = text.splitlines()
        with self._worker_logs_lock:
            ring = self._worker_logs.setdefault(
                wid_hex, {"node": agent.host_key, "lines": deque(maxlen=1000)})
            ring["lines"].extend((stream, ln) for ln in lines)
            # bounded over worker churn: evict the oldest rings past 200 workers
            while len(self._worker_logs) > 200:
                self._worker_logs.pop(next(iter(self._worker_logs)))
        out = _sys.stdout if stream == "out" else _sys.stderr
        for line in lines:
            # graftlint: allow[no-print] log fan-in contract: remote worker output mirrors verbatim onto the driver's own stdout/stderr
            print(f"({wid_hex[:8]}, node={agent.host_key[:8]}) {line}",
                  file=out)

    # -- head restart: agent re-attach (reference NotifyGCSRestart re-sync) -----------
    def _reattach_agent(self, stream, msg) -> bool:
        """An agent that survived a head restart re-joins with its node id,
        live workers, and arena contents. Rebuild the node, re-add its objects
        to the directory, and rebind journaled detached/named actors to their
        still-running worker processes (reference: raylet re-sync after a GCS
        restart — node_manager.proto NotifyGCSRestart,
        gcs_redis_failure_detector.h)."""
        _, node_hex, resources, labels, max_workers, extras = msg
        node_id = NodeID.from_hex(node_hex)
        # READ phase — journaled actor records for this host, by worker id.
        # The KV reads (gcs's own leaf lock, possibly file-journal I/O) stay
        # OUTSIDE self._lock; only the commit below holds it. Read BEFORE the
        # duplicate-handle death path below: that cleanup unjournals actors it
        # declares dead, and a doubly-delivered reregister (welcome-back race)
        # must still rebind from the records the FIRST delivery saw.
        by_wid: Dict[str, Dict[str, Any]] = {}
        for key in self.gcs.kv.keys(namespace="@actors"):
            try:
                rec = cloudpickle.loads(self.gcs.kv.get(key, namespace="@actors"))
            # graftlint: allow[swallowed-exception] corrupt/unreadable journal records are skipped; reattach rebinds the rest
            except Exception:
                continue
            if rec.get("host") == node_hex:
                by_wid[rec["wid"]] = rec
        # a handle for the same node may linger (reconnect raced the death
        # detection): run the full death path first so inflight tasks fail /
        # retry instead of hanging forever — then rebuild below. A blip on a
        # LIVE head keeps the pre-existing conn-EOF-is-node-death semantics.
        with self._lock:
            old = self._agents_by_key.get(node_hex)
        if old is not None:
            self._on_agent_death(old)
        node = RemoteNodeRuntime(self, node_id, resources, labels, max_workers)
        agent = AgentHandle(self, stream, node)
        node.agent = agent
        data_port = (extras or {}).get("data_port")
        if data_port and stream.peer_ip is not None:
            agent.data_addr = (stream.peer_ip, int(data_port))
        stream.on_message = lambda m: self._handle_agent_message(agent, m)
        stream.on_disconnect = lambda: self._on_agent_death(agent)
        candidates = [(wid_hex, accel, by_wid[wid_hex])
                      for wid_hex, accel in (extras or {}).get("workers", ())
                      if wid_hex in by_wid]
        # workers without a journal record ran plain tasks for the dead head:
        # the agent kills everything missing from keep_workers
        keep = [wid_hex for wid_hex, _, _ in candidates]
        # COMMIT phase — the scheduler/router threads read the actor table and
        # worker bindings under self._lock, so every mutation lands inside one
        # locked block, and it must land BEFORE send_welcome_back: the moment
        # the agent hears back it may emit worker_death/from_worker messages,
        # which dispatch through agent.workers on the stream reader thread.
        # Lock-order audit: node.ledger and the gcs registries guard
        # themselves with private leaf locks and never call back into
        # Cluster, so taking them under self._lock cannot invert; the
        # journal/KV I/O stayed above, outside the lock.
        named: List[Tuple[Dict[str, Any], Any]] = []
        rebound = 0
        with self._lock:
            for wid_hex, accel, rec in candidates:
                w = RemoteWorkerHandle(WorkerID.from_hex(wid_hex), agent, node,
                                       accel)
                w.state = "idle"
                node.workers[w.worker_id] = w
                agent.workers[wid_hex] = w
                spec = rec["creation_spec"]
                st = self.actors.get(spec.actor_id)
                if st is None:
                    st = ActorState(spec.actor_id, spec, rec["method_meta"])
                    self.actors[spec.actor_id] = st
                st.state = "alive"
                st.worker = w
                w.actor_id = spec.actor_id
                node.ledger.try_acquire(dict(spec.resources))  # actor-lifetime hold
                w.resources_held = dict(spec.resources)
                if rec.get("name"):
                    named.append((rec, spec.actor_id))
                rebound += 1
            self._nodes[node_id] = node
            if node_id not in self._node_order:
                self._node_order.append(node_id)
            self._agent_conns[stream] = agent
            self._agents_by_key[node_hex] = agent
        try:
            stream.send_welcome_back({"keep_workers": keep})
        except Exception as e:
            # the stream died between reconnect and welcome-back: unwind the
            # just-committed state through the normal death path (fails the
            # rebound workers, drops the node) instead of leaving a live-
            # looking node bound to a dead stream
            import logging as _logging

            _logging.getLogger("ray_tpu.node").warning(
                "node %s reconnect stream died before welcome-back (%r); "
                "unwinding the reattach", node_hex[:8], e)
            self._on_agent_death(agent)
            return False
        for rec, actor_id in named:
            self.gcs.register_named_actor(rec["name"], rec.get("namespace", ""),
                                          actor_id)
        # re-journal ALL rebound actors (named or not): the duplicate-handle
        # death path above may have unjournaled them, and a THIRD replay (or
        # the next head restart) must find current records — the KV put is
        # idempotent
        with self._lock:
            for _, _, rec in candidates:
                st = self.actors.get(rec["creation_spec"].actor_id)
                if st is not None:
                    self._journal_actor(st)
        # the agent's arena contents go back into the directory, pinned (their
        # owner refs died with the old head's drivers). The pin is taken ONCE
        # per (node, object) — a doubly-delivered reregister re-adds the
        # location (idempotent) but must not incref a second time, which
        # would leak the object forever.
        arena_name = (extras or {}).get("arena")
        if arena_name:
            for oid_bytes, size, flags in (extras or {}).get("objects", ()):
                oid = ObjectID(oid_bytes)
                self.store.add(oid, ("remote", node_hex,
                                     ("arena", arena_name, oid_bytes, size,
                                      bool(flags & 1))))
                with self._lock:
                    pinned = (node_hex, oid) in self._reattach_pins
                    self._reattach_pins.add((node_hex, oid))
                if not pinned:
                    self.store.incref(oid)
        self.gcs.register_node(NodeInfo(node_id=node_id, resources=dict(resources),
                                        labels={**(labels or {}), "agent": "remote"}))
        import logging as _logging

        # warning level: head-restart recovery must stay visible under the
        # default (unconfigured) logging, like the print it replaced
        _logging.getLogger("ray_tpu.node").warning(
            "node %s re-attached: %d actors rebound, %d objects re-added",
            node_hex[:8], rebound, len((extras or {}).get("objects", ())))
        if any(rec.get("name") == "SERVE_CONTROLLER" for rec, _ in named):
            # a rebound serve controller means apps are live again: restart
            # the head-side autoscaling loop in THIS head process — its
            # targets re-derive from the controller's restored configs
            try:
                from ray_tpu.serve.autoscaler import ensure_serve_autoscaler

                ensure_serve_autoscaler()
            except Exception as e:  # noqa: BLE001 — serving works unscaled
                _logging.getLogger("ray_tpu.node").warning(
                    "could not restart the serve autoscaler after reattach "
                    "(autoscaling paused until a serve API call): %r", e)
        self._schedule()
        return True

    def _journal_actor(self, st: ActorState) -> None:
        """Persist a remote actor's placement so a restarted head can rebind
        it to its still-running worker (reference: GCS actor table in Redis
        surviving gcs_server restart). EVERY actor hosted on a remote worker
        is journaled, not just named/detached ones — a head restart must be a
        non-event for plain actors too (serve replicas especially: killing
        them at reattach would turn every head blip into a serving gap).
        Known limitation: a plain actor whose owner died WITH the old head
        is rebound anyway and lives until explicitly killed — the restarted
        head has no ownership record to reclaim it by."""
        w = st.worker
        if not isinstance(w, RemoteWorkerHandle):
            return
        try:
            rec = cloudpickle.dumps({
                "name": st.name, "namespace": st.namespace,
                "detached": st.detached, "host": w.node.host_key,
                "wid": w.worker_id.hex(), "method_meta": st.method_meta,
                "creation_spec": st.creation_spec,
            })
            self.gcs.kv.put(st.actor_id.binary(), rec, namespace="@actors")
        # graftlint: allow[swallowed-exception] an unpicklable actor spec must not fail the creation; only head-restart rebind is lost
        except Exception:
            pass  # an unpicklable spec must not fail the creation itself

    def _unjournal_actor(self, st: ActorState) -> None:
        try:
            self.gcs.kv.delete(st.actor_id.binary(), namespace="@actors")
        # graftlint: allow[swallowed-exception] journal delete is best-effort; stale records are skipped on restore
        except Exception:
            pass

    def _handle_agent_message(self, agent: AgentHandle, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "from_worker":
            _, wid_hex, raw = msg
            w = agent.workers.get(wid_hex)
            if w is None:
                return
            self._handle_message(w, cloudpickle.loads(raw))
        elif kind == "worker_death":
            w = agent.workers.pop(msg[1], None)
            if w is not None:
                w.process.dead = True
                self._on_worker_death(w)
        elif kind == "heartbeat":
            agent.last_heartbeat = time.time()
        elif kind == "worker_log":
            self._on_worker_log(agent, msg[1], msg[2], msg[3])
        elif kind == "node_metrics":
            self._on_node_metrics(agent, msg)
        elif kind == "reply":
            agent.on_reply(msg[1], msg[2], msg[3])

    def _on_node_metrics(self, agent: AgentHandle, msg: Tuple) -> None:
        """Consume one pre-aggregated per-node delta (JSON payloads — the
        head never unpickles agent control traffic). The node entry REPLACES
        this agent's per-worker metric entries so the same series are never
        counted twice when an agent upgrades mid-flight."""
        import json as _json

        from ray_tpu.util import metrics as _m
        from ray_tpu.util import telemetry as _tel

        _, seq, agent_time, worker_count, metrics_json, telemetry_json, \
            flush_interval_s = msg
        count = self._note_inlet_frame()
        try:
            snap = _m.snapshot_from_wire(_json.loads(metrics_json or b"[]"))
        # graftlint: allow[swallowed-exception] a malformed delta from one agent must not kill the inlet; the next flush replaces it
        except Exception:
            snap = []
        if snap:
            self.metrics_by_node[agent.host_key] = snap
            # retire this agent's per-worker entries: the node delta is now
            # the canonical source for every series those workers push
            for w in agent.workers.values():
                self.metrics_by_worker.pop(w.worker_id, None)
        ceiling = self._inlet_shed_ceiling()
        if ceiling and count > ceiling:
            # past the hard ceiling: shed the telemetry payload (the bulky
            # part) but keep the cheap metrics snapshot — and say so
            _tel.get_counter(
                "control_inlet_shed_total",
                "telemetry payloads shed at the head's control inlet "
                "(backpressure hard ceiling)").inc()
            return
        try:
            batches = _json.loads(telemetry_json or b"[]")
        # graftlint: allow[swallowed-exception] a malformed delta from one agent must not kill the inlet; the next flush replaces it
        except Exception:
            batches = []
        if batches:
            aligned = []
            for b in batches:
                if not isinstance(b, dict):
                    continue
                wid = str(b.get("wid") or "")[:8]
                aligned.extend(_tel.align_batch(b, f"worker-{wid}"))
            if aligned:
                with self._lock:
                    self.telemetry_events.extend(aligned)

    def _on_agent_death(self, agent: AgentHandle) -> None:
        """A node agent's connection dropped: fail its workers, drop its objects
        (promoting replicas / reconstructing from lineage), remove the node
        (reference: GcsNodeManager node-death path + ObjectRecoveryManager)."""
        with self._lock:
            if not agent.alive and agent.conn not in self._agent_conns:
                return
            self._agent_conns.pop(agent.conn, None)
            self._agents_by_key.pop(agent.host_key, None)
            workers = list(agent.workers.values())
            agent.workers.clear()
        agent.fail_all_pending(f"node agent {agent.host_key[:8]} died")
        self.metrics_by_node.pop(agent.host_key, None)
        err = WorkerCrashedError(f"node {agent.host_key[:8]} died")
        for w in workers:
            w.process.dead = True
            self._on_worker_death(w, err)
        self._drop_host_objects(agent.host_key)
        with self._lock:
            node = self._nodes.get(agent.node.node_id)
            if node is not None:
                node.alive = False
        self.gcs.remove_node(agent.node.node_id)
        self._schedule()

    def _drop_host_objects(self, host_key: str) -> None:
        """Objects whose primary location lived on a dead host: promote a replica
        from a live host if one exists, else reconstruct from lineage, else fail."""
        with self.store._lock:
            dead = [(oid, loc) for oid, loc in self.store._locations.items()
                    if loc[0] == "remote" and loc[1] == host_key]
        with self._transfer_lock:
            for (oid, host), _ in list(self._replicas.items()):
                if host == host_key:
                    self._replicas.pop((oid, host), None)
        for oid, loc in dead:
            promoted = None
            with self._transfer_lock:
                for (o, host), rloc in self._replicas.items():
                    if o == oid and (host == "local" or host in self._agents_by_key):
                        promoted = rloc if host == "local" else ("remote", host, rloc)
                        break
            if promoted is not None:
                self.store.add(oid, promoted)
                continue
            self.store.drop_location(oid)
            if oid in self.lineage:
                # eager reconstruction: location() waiters block until the
                # resubmitted task re-adds a live location
                threading.Thread(target=self._recover_safely, args=(oid,),
                                 daemon=True, name="rt-recover").start()
            else:
                self.store.mark_failed(oid, object_store.ObjectLost(
                    f"object {oid.hex()[:12]} was lost with node {host_key[:8]} "
                    "and has no lineage to reconstruct"))

    def _recover_safely(self, oid: ObjectID) -> None:
        try:
            self._recover_object(oid)
        except Exception as e:  # noqa: BLE001
            self.store.mark_failed(oid, e if isinstance(e, object_store.ObjectLost)
                                   else object_store.ObjectLost(str(e)))

    def _on_remote_free(self, loc) -> None:
        """store._free hook for ("remote", host, inner) primaries."""
        agent = self._agents_by_key.get(loc[1])
        if agent is not None:
            try:
                agent.send(("free_object", loc[2]))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass

    # -- cross-host object localization (reference object_manager.h:119) ---------------
    @staticmethod
    def _loc_host(loc) -> str:
        return loc[1] if loc[0] == "remote" else "local"

    @staticmethod
    def _worker_host(w: Optional[WorkerHandle]) -> str:
        return w.node.host_key if w is not None else "local"

    def _wrap_loc(self, w: WorkerHandle, loc) -> Tuple:
        """Locations registered by a remote host's worker are tagged with that
        host so the directory knows where the bytes physically live."""
        if loc[0] == "inline" or not isinstance(w, RemoteWorkerHandle):
            return loc
        return ("remote", w.node.host_key, loc)

    def _localize(self, oid: ObjectID, dest_host: str, timeout: Optional[float] = None):
        """Return a location readable on dest_host, transferring bytes if the
        object lives elsewhere (head-mediated fetch/store; reference PullManager
        + ObjectManager push). Concurrent requests for the same (oid, host)
        dedup onto one transfer. A fetch from a dead host drops the stale
        primary and reconstructs from lineage before retrying (reference
        ObjectRecoveryManager)."""
        last_err: Optional[BaseException] = None
        for _ in range(3):
            loc = self.store.location(oid, timeout)
            if loc[0] == "inline" or self._loc_host(loc) == dest_host:
                return loc[2] if loc[0] == "remote" else loc
            try:
                return self._transfer_dedup(oid, loc, dest_host)
            except object_store.ObjectLost as e:
                last_err = e
                # the primary's host died under us: forget it (CAS — a parallel
                # recovery may already have re-added a fresh one) and reconstruct
                with self.store._lock:
                    if self.store._locations.get(oid) == loc:
                        self.store._locations.pop(oid)
                self._recover_object(oid)  # raises ObjectLost when no lineage
        raise last_err

    def _localize_many(self, oids: List[ObjectID], dest_host: str,
                       timeout: Optional[float] = None) -> List:
        """_localize for a batch, overlapping the cross-host transfers."""
        locs = [self.store.location(oid, timeout) for oid in oids]
        needs = [oid for oid, loc in zip(oids, locs)
                 if loc[0] == "remote" and loc[1] != dest_host]
        # warm the replica cache concurrently; the serial pass below then
        # returns each replica instantly
        self._pull_batch(needs, dest_host, timeout)
        return [self._localize(oid, dest_host, timeout) for oid in oids]

    def _pull_batch(self, oids: List[ObjectID], dest_host: str,
                    timeout: Optional[float]) -> None:
        """Transfer a set of objects to dest_host, overlapping the pulls
        (reference PullManager issues pulls concurrently)."""
        if not oids:
            return
        if len(oids) == 1:
            self._localize(oids[0], dest_host, timeout)
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(oids))) as ex:
            list(ex.map(lambda o: self._localize(o, dest_host, timeout), oids))

    def _transfer_dedup(self, oid: ObjectID, loc, dest_host: str):
        while True:
            with self._transfer_lock:
                replica = self._replicas.get((oid, dest_host))
                if replica is not None:
                    return replica
                ev = self._transfers.get((oid, dest_host))
                mine = ev is None
                if mine:
                    ev = threading.Event()
                    self._transfers[(oid, dest_host)] = ev
            if not mine:
                # must outlast the winner's WORST case: two direct-pull
                # attempts (DataClient retries once on a stale pooled conn)
                # plus the relay fallback behind them (fetch_object +
                # store_object, 60s control-RPC each)
                if not ev.wait(timeout=2 * CONFIG.transfer_timeout_s + 180.0):
                    raise TimeoutError(
                        f"transfer of {oid.hex()[:12]} to {dest_host[:8]} timed out")
                continue  # re-check: winner registered a replica, or failed and we retry
            try:
                new_loc = self._do_transfer(oid, loc, dest_host)
            except BaseException:
                with self._transfer_lock:
                    self._transfers.pop((oid, dest_host), None)
                ev.set()
                raise
            with self._transfer_lock:
                self._replicas[(oid, dest_host)] = new_loc
                self._transfers.pop((oid, dest_host), None)
            ev.set()
            return new_loc

    def _do_transfer(self, oid: ObjectID, loc, dest_host: str):
        """Move one object's bytes to dest_host. Preferred path: the DESTINATION
        pulls chunked straight from the source's data server — the head only
        brokers (src ip, port, location) and the bytes never transit this
        process (reference object_manager.h:119 direct transfers). Head relay
        over the control channel remains the fallback for agents without a data
        plane or when the direct pull fails."""
        src_host = self._loc_host(loc)
        inner = loc[2] if loc[0] == "remote" else loc
        src_agent = None
        if src_host != "local":
            src_agent = self._agents_by_key.get(src_host)
            if src_agent is None:
                raise object_store.ObjectLost(
                    f"object {oid.hex()[:12]} lives on dead node {src_host[:8]}")
        if dest_host == "local":
            # the head itself needs the bytes: striped zero-copy pull straight
            # from the source's data server into this process's own backing
            # (object_store.pull_to_store — no intermediate bytes object)
            if src_agent.data_addr is not None and self._data_client is not None:
                try:
                    return object_store.pull_to_store(
                        self._data_client, src_agent.data_addr, inner, oid)
                except (OSError, EOFError, TimeoutError):
                    pass  # relay fallback below keeps the old error semantics
            data, is_error = self._relay_fetch(src_agent, inner, oid, src_host)
            return object_store.write_raw(data, oid, is_error)
        dest_agent = self._agents_by_key.get(dest_host)
        if dest_agent is None:
            raise OSError(f"destination node {dest_host[:8]} is gone")
        # direct agent->agent (or head->agent) pull
        if dest_agent.data_addr is not None:
            if src_host == "local" and self._data_server is not None:
                # src is this head process; the agent substitutes the head IP
                # it already dials for control traffic
                src_addr = (None, self._data_server.port)
            else:
                src_addr = src_agent.data_addr if src_agent is not None else None
            if src_addr is not None:
                try:
                    return dest_agent.call("pull_object", oid, inner, src_addr,
                                           timeout=CONFIG.transfer_timeout_s)
                except (OSError, EOFError, TimeoutError):
                    pass  # relay fallback
        # head-relay fallback: whole object through this process
        if src_host == "local":
            data, is_error = object_store.read_raw(loc)
        else:
            data, is_error = self._relay_fetch(src_agent, inner, oid, src_host)
        return dest_agent.call("store_object", oid, data, is_error)

    @staticmethod
    def _relay_fetch(src_agent: AgentHandle, inner, oid: ObjectID, src_host: str):
        """Whole-object fetch over the source agent's control channel. A
        fetch-side failure means the bytes are unreachable: raise ObjectLost so
        the caller's recovery path reconstructs from lineage."""
        try:
            return src_agent.call("fetch_object", inner)
        except (OSError, EOFError, TimeoutError) as e:
            raise object_store.ObjectLost(
                f"fetching {oid.hex()[:12]} from node {src_host[:8]} "
                f"failed: {e}") from e

    # -- router (multiplexes all worker pipes) ----------------------------------------
    def _register_conn(self, w: WorkerHandle) -> None:
        with self._lock:
            self._conns[w.conn] = w
        try:
            self._wakeup_w.send_bytes(b"x")
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def _router(self) -> None:
        # local worker pipes only: agent streams are gRPC — their reader
        # threads call _handle_agent_message / _on_agent_death directly
        while not self._shutdown:
            with self._lock:
                conns = list(self._conns.keys())
            ready = multiprocessing.connection.wait([self._wakeup_r] + conns, timeout=1.0)
            for conn in ready:
                if conn is self._wakeup_r:
                    try:
                        self._wakeup_r.recv_bytes()
                    # graftlint: allow[swallowed-exception] wakeup-pipe drain: a torn self-pipe only costs one extra poll
                    except Exception:
                        pass
                    continue
                with self._lock:
                    w = self._conns.get(conn)
                if w is None:
                    continue
                try:
                    raw = conn.recv_bytes()
                except (EOFError, OSError):
                    self._on_worker_death(w)
                    continue
                try:
                    msg = cloudpickle.loads(raw)
                    self._handle_message(w, msg)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _handle_message(self, w: WorkerHandle, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "ready":
            with self._lock:
                if w.state == "starting":
                    w.node.push_idle(w)
            self._schedule()
        elif kind == "result":
            self._on_result(w, msg[1], msg[2], msg[3])
        elif kind == "submit":
            self.submit(msg[1])
        elif kind == "get":
            _, req_id, oids, timeout = msg
            host = self._worker_host(w)
            self._async_reply(w, req_id,
                              lambda: self._localize_many(oids, host, timeout),
                              blocking=True)
        elif kind == "wait":
            _, req_id, oids, num_returns, timeout = msg
            self._async_reply(w, req_id, lambda: self.store.wait(oids, num_returns, timeout),
                              blocking=True)
        elif kind == "put":
            _, oid, loc = msg
            self.store.add(oid, self._wrap_loc(w, loc))
            self.store.incref(oid)
            self._schedule()
        elif kind == "stream":
            # one yielded item of a streaming generator task; owned by the
            # consumer-side ObjectRefGenerator (decref on its ref's GC)
            _, task_id, index, oid, loc = msg
            self.store.add(oid, self._wrap_loc(w, loc))
            self.store.incref(oid)
            with self._lock:
                self._stream_counts[task_id] = index + 1
                abandoned = self._stream_abandoned.get(task_id)
            if abandoned is not None and index >= abandoned:
                self.store.decref(oid)  # consumer is gone: don't pin the item
                # ... and stop the producer (once): without this, an abandoned
                # stream (disconnected SSE client) keeps generating to
                # max_tokens, holding engine resources the whole time. Once-only
                # so a cancel landing after the producer finished can't leak a
                # stale id into the worker's cancelled set per late item.
                with self._lock:
                    send_cancel = task_id not in self._stream_cancel_sent
                    if send_cancel:
                        self._stream_cancel_sent.add(task_id)
                if send_cancel:
                    try:
                        w.send(("cancel_stream", task_id))
                    # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                    except Exception:
                        pass
            self._schedule()  # tasks may be waiting on this item ref as an arg
        elif kind == "drop_stream":
            self.drop_stream(msg[1], msg[2])
        elif kind == "decref":
            self.store.decref(msg[1])
        elif kind == "incref":
            # explicit pin (stream handoff): released by the adopter's owned ref
            self.store.incref(msg[1])
        elif kind == "recover":
            _, req_id, oid = msg
            host = self._worker_host(w)
            self._async_reply(
                w, req_id,
                lambda: (self._recover_object(oid), self._localize(oid, host, 60.0))[1],
                blocking=True)
        elif kind == "state":
            _, req_id, fn_name, fargs, fkwargs = msg

            def run_state(fn_name=fn_name, fargs=fargs, fkwargs=fkwargs):
                from ray_tpu.util.state import dispatch_state_request

                return dispatch_state_request(fn_name, fargs, fkwargs)

            self._async_reply(w, req_id, run_state)
        elif kind == "stacks":
            _, token, worker_id_hex, text = msg
            with self._lock:
                if token in self._stack_dumps:  # late replies after timeout are dropped
                    self._stack_dumps[token][worker_id_hex] = text
        elif kind == "metrics":
            # periodic per-worker metric snapshot (util/metrics.py push thread)
            self._note_inlet_frame()
            self.metrics_by_worker[w.worker_id] = msg[1]
        elif kind == "collective_join":
            _, group, rank, epoch = msg
            with self._lock:
                self._collective_members.setdefault(group, {})[rank] = (w, epoch)
        elif kind == "collective_leave":
            _, group, rank, epoch = msg
            with self._lock:
                members = self._collective_members.get(group)
                # only the registered incarnation may retract itself: a fresh
                # join for the same rank (group re-init on another worker) must
                # not be clobbered by the old member's late destroy
                if members and members.get(rank) == (w, epoch):
                    members.pop(rank, None)
                    if not members:
                        self._collective_members.pop(group, None)
        elif kind == "tqdm":
            from ray_tpu.experimental.tqdm_ray import _render_local

            _render_local(msg[1])
        elif kind == "spans":
            with self._lock:  # readers iterate under the same lock (state.get_trace)
                self.trace_spans.extend(msg[1])
        elif kind == "telemetry":
            # hot-path event batch (util/telemetry.py flush): clock-align and
            # proc-tag here, once, so every reader sees one merged timeline
            from ray_tpu.util import telemetry as _tel

            count = self._note_inlet_frame()
            ceiling = self._inlet_shed_ceiling()
            if ceiling and count > ceiling:
                _tel.get_counter(
                    "control_inlet_shed_total",
                    "telemetry payloads shed at the head's control inlet "
                    "(backpressure hard ceiling)").inc()
                return
            aligned = _tel.align_batch(msg[1], f"worker-{w.worker_id.hex()[:8]}")
            with self._lock:
                self.telemetry_events.extend(aligned)
        elif kind == "kv":
            _, req_id, op = msg[:3]
            args = msg[3:]
            try:
                self._reply(w, req_id, True, getattr(self.gcs.kv, op)(*args))
            except Exception as e:  # noqa: BLE001
                self._reply(w, req_id, False, e)
        elif kind == "register_fn":
            _, fn_id, fn_bytes = msg
            self._register_fn(fn_id, fn_bytes)
            w.known_fns.add(fn_id)
        elif kind == "fetch_fn":
            _, req_id, fn_id = msg
            fn_bytes = self.fn_table.get(fn_id)
            if fn_bytes is None:
                self._reply(w, req_id, False, KeyError(f"unknown function {fn_id.hex()[:12]}"))
            else:
                w.known_fns.add(fn_id)
                self._reply(w, req_id, True, fn_bytes)
        elif kind == "kill_actor":
            self.kill_actor(msg[1], no_restart=msg[2], from_gc=msg[3] if len(msg) > 3 else False)
        elif kind == "cancel":
            self.cancel(msg[1], force=msg[2])
        elif kind == "get_named_actor":
            _, req_id, name, namespace = msg
            try:
                handle = self.get_named_actor_handle(name, namespace)
                self._reply(w, req_id, True, handle)
            except Exception as e:  # noqa: BLE001
                self._reply(w, req_id, False, e)
        elif kind == "lookup_pg":
            _, req_id, pg_id = msg
            pg = self.pg_manager.lookup(pg_id)
            if pg is None:
                with self._lock:
                    pg = next((p for p in self.pending_pgs if p.id == pg_id), None)
            data = None
            if pg is not None:
                data = (pg.bundle_specs, pg.strategy, pg.name, pg.is_ready, pg._failed)
            self._reply(w, req_id, True, data)
        elif kind == "pg_ready_ref":
            _, req_id, pg_id = msg
            self._async_reply(w, req_id, lambda: self._pg_ready_blocking(pg_id), blocking=True)
        elif kind == "create_pg":
            _, req_id, bundles, strategy, name = msg
            pg = self.create_placement_group(bundles, strategy, name)
            self._reply(w, req_id, True, pg.id)
        elif kind == "remove_pg":
            self.remove_placement_group(msg[1])

    def _reply(self, w: WorkerHandle, req_id: int, ok: bool, value) -> None:
        try:
            w.send(("reply", req_id, ok, value))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def _async_reply(self, w: WorkerHandle, req_id: int, fn, blocking: bool = False) -> None:
        """Run fn on a waiter thread and reply; a blocking worker releases its resources."""
        if blocking:
            self._mark_blocked(w)

        def run():
            try:
                value = fn()
                ok = True
            except BaseException as e:  # noqa: BLE001
                value, ok = e, False
            if blocking:
                self._unmark_blocked(w)
            self._reply(w, req_id, ok, value)

        threading.Thread(target=run, daemon=True,
                         name="node-actor-call").start()

    def _mark_blocked(self, w: WorkerHandle) -> None:
        with self._lock:
            if w.state == "busy" and not w.blocked_reqs:
                w.state = "blocked"
                if w.resources_held:
                    (w.bundle_ledger or w.node.ledger).release(w.resources_held)
            w.blocked_reqs.add(threading.get_ident())
        self._schedule()

    def _unmark_blocked(self, w: WorkerHandle) -> None:
        with self._lock:
            w.blocked_reqs.discard(threading.get_ident())
            if w.state == "blocked" and not w.blocked_reqs:
                w.state = "busy"
                if w.resources_held:
                    (w.bundle_ledger or w.node.ledger).force_acquire(w.resources_held)

    def _pg_ready_blocking(self, pg_id: PlacementGroupID):
        pg = self.pg_manager.lookup(pg_id)
        if pg is None:
            with self._lock:
                pg = next((p for p in self.pending_pgs if p.id == pg_id), None)
        if pg is None:
            raise ValueError(f"unknown placement group {pg_id!r}")
        pg.wait(None)
        return True

    # -- submission --------------------------------------------------------------------
    def _register_fn(self, fn_id: bytes, fn_bytes: bytes) -> None:
        """Every function-table write lands here so the bytes also reach the
        GCS KV journal (`@fns`). Senders dedup register_fn per head lifetime;
        durability is the head's job — a restarted head that forgot a class
        can never start a replacement replica or restart an actor."""
        if fn_id in self.fn_table:
            return
        self.fn_table[fn_id] = fn_bytes
        try:
            self.gcs.kv.put(fn_id, fn_bytes, namespace="@fns")
        # graftlint: allow[swallowed-exception] journal I/O failure degrades to the in-memory table, not an error on the hot submit path
        except Exception:
            pass

    def submit(self, spec: TaskSpec) -> None:
        for oid in spec.return_ids:
            self.store.incref(oid)
        if spec.num_returns == -1:
            # streaming: stream bookkeeping lives until the completion object dies
            with self._lock:
                self._stream_completion[spec.return_ids[0]] = spec.task_id
        # Pin args until the task reaches a terminal state (reference: TaskManager holds
        # dependencies for retryable tasks, task_manager.cc).
        for oid in spec.arg_refs:
            self.store.incref(oid)
        if spec.fn_bytes is not None:
            self._register_fn(spec.fn_id, spec.fn_bytes)
        if spec.kind == "task" and spec.max_retries > 0:
            # lineage for reconstruction: snapshot arg_refs now (the live spec's
            # list is cleared when args are unpinned after completion) and pin
            # them for as long as any downstream return oid is in scope, so
            # re-execution always finds its inputs (reference lineage pinning)
            lineage_spec = copy.copy(spec)
            lineage_spec.arg_refs = list(spec.arg_refs)
            for oid in spec.return_ids:
                if oid in self.lineage:
                    continue  # resubmission: original entry already holds the pins
                self.lineage[oid] = lineage_spec
                for arg in lineage_spec.arg_refs:
                    self.store.incref(arg)
        with self._lock:
            self.tasks[spec.task_id] = TaskState(spec)
            if spec.kind == "actor_creation":
                st = ActorState(spec.actor_id, spec, method_meta=spec.method_meta)
                self.actors[spec.actor_id] = st
                if spec.actor_name:
                    ok = self.gcs.register_named_actor(spec.actor_name, spec.actor_namespace, spec.actor_id)
                    if not ok:
                        # Mark the loser DEAD, not pending-forever: method calls
                        # on its handle must fail fast (ActorDiedError), or a
                        # name-race loser probing its handle hangs to timeout.
                        err = ValueError(f"actor name {spec.actor_name!r} already taken")
                        st.state = "dead"
                        st.death_cause = err
                        self._fail_returns(spec, err)
                        return
            # fast path (reference: lease request straight to the local raylet):
            # with no same-shape task queued ahead, dispatch NOW — the common
            # uncongested case never pays a full scheduling pass
            if not self._pending_shape_counts.get(self._shape_key(spec)):
                if self._try_dispatch(spec):
                    return
            self._pending_append(spec)
        if spec.kind == "actor_creation":
            self._schedule()  # creations may need PG placement to run first

    def _shape_key(self, spec: TaskSpec):
        """THE key for _pending_shape_counts — every site must use this one
        derivation or the waiting-count invariant silently breaks."""
        shape = self._placement_shape(spec)
        return shape if shape is not None else ("pg-task", spec.task_id)

    def _pending_append(self, spec: TaskSpec) -> None:
        """Caller holds the lock."""
        key = self._shape_key(spec)
        self._pending_shape_counts[key] = self._pending_shape_counts.get(key, 0) + 1
        self.pending.append(spec)

    def _rebuild_shape_counts(self) -> None:
        """Caller holds the lock; used by rare bulk-mutation paths (drain)."""
        counts: Dict[Any, int] = {}
        for spec in self.pending:
            key = self._shape_key(spec)
            counts[key] = counts.get(key, 0) + 1
        self._pending_shape_counts = counts

    # -- scheduling --------------------------------------------------------------------
    def _schedule(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            # Try to place pending placement groups first (they gate dependent tasks).
            still_pgs = []
            for pg in self.pending_pgs:
                nodes = [(n.node_id, n.ledger) for n in self.nodes()]
                if not self.pg_manager.try_place(pg, nodes):
                    still_pgs.append(pg)
            self.pending_pgs = still_pgs

            # Shape-based skip (reference: per-scheduling-class queues in
            # cluster_task_manager): once a resource shape fails to place, every
            # later task with the same shape is skipped without re-running
            # placement — a 10k-deep homogeneous queue costs one failed attempt
            # per pass instead of 10k.
            # hopeful = waiting shapes not yet known blocked this pass; when it
            # hits zero, splice the rest over at C speed instead of rotating
            # task by task — a 10k-deep homogeneous backlog costs one placement
            # attempt. Tracked incrementally: rebuilding the waiting set per
            # popped task would make the pass O(pending x shapes).
            blocked_shapes: set = set()
            hopeful = len(self._pending_shape_counts)
            remaining = deque()
            while self.pending:
                if hopeful <= 0:
                    remaining.extend(self.pending)
                    self.pending.clear()
                    break
                spec = self.pending.popleft()
                ts = self.tasks.get(spec.task_id)
                key = self._shape_key(spec)
                if ts is None or ts.cancelled:
                    # terminal (failed during arg localization) or cancelled
                    hopeful -= self._dec_shape(key, blocked_shapes)
                    continue
                if key in blocked_shapes:
                    remaining.append(spec)
                    continue
                if not self._try_dispatch(spec):
                    remaining.append(spec)
                    if not self._dispatch_blocked_on_args:
                        blocked_shapes.add(key)
                        hopeful -= 1
                else:
                    hopeful -= self._dec_shape(key, blocked_shapes)
            self.pending = remaining

    def _dec_shape(self, key, blocked_shapes: set) -> int:
        """Decrement a shape's waiting count; returns 1 when the shape just
        emptied while still hopeful (caller shrinks its hopeful counter)."""
        c = self._pending_shape_counts.get(key, 0) - 1
        if c > 0:
            self._pending_shape_counts[key] = c
            return 0
        self._pending_shape_counts.pop(key, None)
        return 0 if key in blocked_shapes else 1

    @staticmethod
    def _placement_shape(spec: TaskSpec):
        """Hashable key for 'tasks that compete for identical placement'; None
        when feasibility is task-specific (PG bundles)."""
        if spec.kind == "actor_method":
            return ("actor", spec.actor_id)
        strategy = spec.scheduling_strategy
        if isinstance(strategy, PlacementGroupSchedulingStrategy) or spec.pg_id is not None:
            return None
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            skey = ("affinity", strategy.node_id, strategy.soft)
        elif isinstance(strategy, NodeLabelSchedulingStrategy):
            # dict-bearing dataclass is unhashable; repr is stable per shape
            skey = ("labels", repr(strategy.hard), repr(strategy.soft))
        else:
            skey = (strategy,)
        return (spec.kind, skey, tuple(sorted(spec.resources.items())))

    def _args_ready(self, spec: TaskSpec) -> Tuple[str, Optional[List]]:
        """Returns ("ready", locs) | ("pending", None) | ("failed", None)."""
        locs = []
        for oid in spec.arg_refs:
            try:
                loc = self.store.try_location(oid)
            except Exception as e:  # noqa: BLE001  -- an arg failed: propagate to returns
                self._fail_returns(spec, e)
                return "failed", None
            if loc is None:
                return "pending", None
            locs.append(loc)
        return "ready", locs

    def _try_dispatch(self, spec: TaskSpec) -> bool:
        """Returns True if the task left the pending queue (dispatched or failed).
        Sets _dispatch_blocked_on_args when False is task-specific (args/
        transfer pending) rather than a resource-shape failure."""
        self._dispatch_blocked_on_args = False
        if spec.kind == "actor_method":
            return self._try_dispatch_actor_method(spec)

        status, locs = self._args_ready(spec)
        if status == "failed":
            return True
        if status == "pending":
            self._dispatch_blocked_on_args = True
            return False

        placement = self._choose_placement(spec)
        if placement is None:
            return False
        node, ledger, resources = placement
        locs = self._localize_args_or_defer(spec, locs, node.host_key)
        if locs is None:
            ledger.release(resources)
            self._dispatch_blocked_on_args = True
            return False  # transfer in flight; rescheduled when it lands
        accel = "tpu" if resources.get("TPU", 0) > 0 else "cpu"
        # Tasks with runtime_env env_vars get a DEDICATED worker pool keyed by
        # the env hash (reference: worker-per-runtime-env): process-level vars
        # (XLA_FLAGS, JAX_PLATFORMS, ...) only take effect at process spawn, so
        # a reused plain worker must never serve an env_vars task.
        renv = spec.runtime_env if isinstance(spec.runtime_env, dict) else None
        env_vars = (renv or {}).get("env_vars")
        from .container import (ContainerRuntimeError, normalize_container_spec)

        try:
            container = normalize_container_spec(renv)
        except ValueError as e:
            ledger.release(resources)
            self._fail_returns(spec, e)
            return True
        if env_vars or container:
            import hashlib as _hashlib
            import json as _json

            ek = _hashlib.sha256(_json.dumps(
                {"env": env_vars, "container": container}, sort_keys=True)
                .encode()).hexdigest()[:10]
            pool_key = f"{accel}|env:{ek}"
        else:
            pool_key = accel
        worker = node.pop_idle(pool_key)
        if worker is None:
            try:
                worker = node.spawn_worker(accel, extra_env=env_vars or None,
                                           pool_key=pool_key,
                                           container=container)
                if worker is None and len(node.workers) >= node.max_workers:
                    # cap reached with every slot held by other pools' idle
                    # workers: evict one to admit this pool, else the task
                    # would queue forever (the eviction victim is idle — no
                    # inflight work is lost). Guarded on the cap so a remote
                    # spawn failure (dead agent, send error) doesn't drain
                    # warm workers for nothing.
                    victim = node.steal_idle_slot(pool_key)
                    if victim is not None:
                        self._kill_worker(victim, WorkerCrashedError(
                            "idle worker evicted to admit a new worker pool"))
                        worker = node.spawn_worker(
                            accel, extra_env=env_vars or None,
                            pool_key=pool_key, container=container)
            except ContainerRuntimeError as e:
                # env setup failure fails the TASK (reference: runtime-env
                # agent setup errors), not the scheduler
                ledger.release(resources)
                self._fail_returns(spec, e)
                return True
            if worker is None:
                ledger.release(resources)
                return False
            # Worker is starting; it will announce "ready". Reserve it for this task by
            # dispatching immediately — the pipe buffers until the worker loop starts.
        worker.state = "busy"
        worker.resources_held = resources
        worker.bundle_ledger = ledger if ledger is not node.ledger else None
        self._send_task(worker, spec, locs)
        ts = self.tasks.get(spec.task_id)
        if ts is None:
            # send failed with the task marked failed: free the reserved worker
            ledger.release(resources)
            worker.resources_held = {}
            worker.bundle_ledger = None
            node.push_idle(worker)
            return True
        ts.worker = worker
        ts.resources_node = node
        ts.resources = resources
        ts.bundle_ledger = worker.bundle_ledger
        if spec.kind == "actor_creation":
            st = self.actors[spec.actor_id]
            st.worker = worker
            worker.actor_id = spec.actor_id
        return True

    def _try_dispatch_actor_method(self, spec: TaskSpec) -> bool:
        st = self.actors.get(spec.actor_id)
        if st is None or st.state == "dead":
            cause = st.death_cause if st else None
            self._fail_returns(spec, ActorDiedError(f"actor {spec.actor_id!r} is dead: {cause!r}"))
            return True
        if st.state != "alive":
            return False  # queued until creation finishes / restart completes
        status, locs = self._args_ready(spec)
        if status == "failed":
            return True
        if status == "pending":
            self._dispatch_blocked_on_args = True
            return False
        locs = self._localize_args_or_defer(spec, locs, st.worker.node.host_key)
        if locs is None:
            self._dispatch_blocked_on_args = True
            return False  # transfer in flight; rescheduled when it lands
        self._send_task(st.worker, spec, locs)
        ts = self.tasks.get(spec.task_id)
        if ts is None:
            return True  # send failed; returns were failed, actor stays pinned
        ts.worker = st.worker
        return True

    def _localize_args_or_defer(self, spec: TaskSpec, locs: List, host: str) -> Optional[List]:
        """Host-local locations for every arg, or None after kicking off the
        needed transfers in the background (the scheduler must never block on a
        cross-host copy — reference: DependencyManager pulls args asynchronously
        before a lease is granted, raylet/dependency_manager.h)."""
        out = []
        missing = []
        for oid, loc in zip(spec.arg_refs, locs):
            if loc[0] == "inline" or self._loc_host(loc) == host:
                out.append(loc[2] if loc[0] == "remote" else loc)
                continue
            with self._transfer_lock:
                replica = self._replicas.get((oid, host))
            if replica is not None:
                out.append(replica)
            else:
                missing.append(oid)
        if not missing:
            return out
        # keyed by (task, host): if the destination dies mid-pull the next
        # placement (a different host) must be able to start its own pull
        pull_key = (spec.task_id, host)
        if pull_key not in self._localizing:
            self._localizing.add(pull_key)

            def pull(missing=missing, spec=spec, host=host):
                try:
                    self._pull_batch(missing, host,
                                     timeout=CONFIG.localize_pull_timeout_s)
                    self._pull_failures.pop(spec.task_id, None)
                except object_store.ObjectLost as e:
                    # unreconstructible (no lineage): the task can never run
                    self._fail_returns(spec, e)
                except BaseException as e:  # noqa: BLE001
                    # usually transient (dest host died, transfer timeout): the
                    # reschedule below re-places the task and pulls afresh — but
                    # bounded, so a persistently failing transfer surfaces to
                    # the caller instead of hanging its get() forever
                    n = self._pull_failures.get(spec.task_id, 0) + 1
                    self._pull_failures[spec.task_id] = n
                    if n >= 3:
                        self._pull_failures.pop(spec.task_id, None)
                        self._fail_returns(spec, e if isinstance(e, Exception)
                                           else RuntimeError(str(e)))
                finally:
                    self._localizing.discard(pull_key)
                    self._schedule()

            threading.Thread(target=pull, daemon=True, name="rt-arg-pull").start()
        return None

    def _send_task(self, worker: WorkerHandle, spec: TaskSpec, locs: List) -> None:
        if spec.fn_id in worker.known_fns:
            spec.fn_bytes = None
        else:
            spec.fn_bytes = self.fn_table.get(spec.fn_id, spec.fn_bytes)
            worker.known_fns.add(spec.fn_id)
        worker.inflight.append(spec.task_id)
        ts = self.tasks.get(spec.task_id)
        if ts is not None:
            ts.dispatched_at = time.time()
        try:
            worker.send(("task", spec, locs))
        except (OSError, BrokenPipeError, EOFError):
            # dying pipe: the spec is already in w.inflight, so the worker-death
            # handler will fail or retry it — losing the exception here would
            # otherwise strand the task's returns forever
            pass
        except Exception as e:  # e.g. unpicklable args: worker is healthy, fail visibly
            try:
                worker.inflight.remove(spec.task_id)
            except ValueError:
                pass
            # the worker never received the fn bytes
            worker.known_fns.discard(spec.fn_id)
            self._fail_returns(spec, e)  # pops self.tasks — callers must re-check

    def _choose_placement(self, spec: TaskSpec):
        """Pick (node, ledger, resources) honoring the scheduling strategy; None = wait."""
        strategy = spec.scheduling_strategy
        resources = dict(spec.resources)
        if isinstance(strategy, PlacementGroupSchedulingStrategy) or spec.pg_id is not None:
            pg_id = spec.pg_id or strategy.placement_group.id
            bundle_index = spec.pg_bundle_index if spec.pg_id else strategy.placement_group_bundle_index
            bundles = self.pg_manager.bundles(pg_id)
            if not bundles:
                return None  # PG not placed yet
            candidates = bundles if bundle_index < 0 else [bundles[bundle_index]]
            for b in candidates:
                if b.ledger.try_acquire(resources):
                    node = self._nodes.get(b.node_id)
                    if node is None or not node.alive:
                        b.ledger.release(resources)
                        continue
                    return node, b.ledger, resources
            return None
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            node = self._nodes.get(NodeID.from_hex(strategy.node_id))
            if node is not None and node.alive and node.ledger.try_acquire(resources):
                return node, node.ledger, resources
            if not strategy.soft:
                if node is None or not node.alive:
                    self._fail_returns(spec, WorkerCrashedError(f"node {strategy.node_id} unavailable"))
                return None
            # soft: fall through to default
        if isinstance(strategy, NodeLabelSchedulingStrategy):
            # reference scheduling_strategies.py:135: hard terms filter, soft
            # terms rank; no hard match -> wait (a labeled node may join later)
            candidates = [n for n in self.nodes() if strategy.hard_match(n.labels)]
            if not candidates:
                return None
            candidates.sort(key=lambda n: (not strategy.soft_match(n.labels),
                                           n.ledger.utilization()))
            for node in candidates:
                if node.ledger.try_acquire(resources):
                    return node, node.ledger, resources
            return None
        nodes = self.nodes()
        if not nodes:
            return None
        if strategy == "SPREAD":
            start = next(self._spread_counter) % len(nodes)
            ordered = nodes[start:] + nodes[:start]
        else:
            # Hybrid default: prefer the head node, then least-utilized (reference:
            # hybrid_scheduling_policy.h — prefer local, spill to top-k by utilization).
            ordered = sorted(nodes, key=lambda n: (n is not self.head_node, n.ledger.utilization()))
        for node in ordered:
            if node.ledger.try_acquire(resources):
                return node, node.ledger, resources
        return None

    # -- results & failure -------------------------------------------------------------
    def _on_result(self, w: WorkerHandle, task_id: TaskID, payload, err_info) -> None:
        payload = [(oid, self._wrap_loc(w, loc)) for oid, loc in payload]
        with self._lock:
            ts = self.tasks.get(task_id)
            if w.inflight and w.inflight[0] == task_id:
                w.inflight.popleft()
            elif task_id in w.inflight:
                # threaded actors (max_concurrency>1) complete methods out of order
                w.inflight.remove(task_id)
        spec = ts.spec if ts else None

        # Application exceptions retry only when retry_exceptions is set (reference
        # semantics: max_retries covers worker crashes; see _on_worker_death).
        retry = (
            err_info is not None
            and spec is not None
            and spec.retry_exceptions
            and spec.attempt < spec.max_retries
        )
        if retry:
            for oid, loc in payload:
                if loc[0] == "remote":
                    self._on_remote_free(loc)
                else:
                    object_store.free_local(loc)
            spec.attempt += 1
            with self._lock:
                self._pending_append(spec)
        else:
            for oid, loc in payload:
                self.store.add(oid, loc)

        with self._lock:
            if spec is not None and spec.kind == "actor_creation":
                st = self.actors.get(spec.actor_id)
                if st is not None:
                    if err_info is None:
                        st.state = "alive"
                        st.worker = w
                        self._journal_actor(st)
                        if st.kill_on_creation:
                            threading.Thread(
                                target=self.kill_actor, args=(st.actor_id, True), daemon=True,
                                name="node-kill-on-creation",
                            ).start()
                    elif not retry:
                        st.state = "dead"
                        st.death_cause = RuntimeError(f"actor creation failed: {err_info[1]}")
                        self._unjournal_actor(st)
                        self._drain_actor_queue_locked(st)
                # Actor worker stays busy/pinned; resources held for actor lifetime.
            elif spec is not None and spec.kind == "actor_method":
                pass  # no per-method resources
            elif ts is not None and ts.resources:
                (ts.bundle_ledger or ts.resources_node.ledger).release(ts.resources)
                w.resources_held = {}
                w.bundle_ledger = None
            if spec is not None and spec.kind == "task" and w.state in ("busy", "blocked"):
                w.node.push_idle(w)
            if not retry and ts is not None:
                self.task_events.append({
                    "task_id": task_id.hex(),
                    "name": ts.spec.name,
                    "kind": ts.spec.kind,
                    "worker_id": w.worker_id.hex(),
                    "node_id": w.node.node_id.hex(),
                    "submitted_at": ts.submitted_at,
                    "dispatched_at": ts.dispatched_at,
                    "finished_at": time.time(),
                    "error": err_info[2] if err_info else None,
                })
                self.tasks.pop(task_id, None)
            if not retry and spec is not None:
                if not (spec.kind == "actor_creation" and spec.max_restarts != 0):
                    # Actor-creation args stay pinned while restarts remain (the
                    # creation spec is resubmitted with the same arg refs).
                    self._unpin_args(spec)
            if (not retry and spec is not None and spec.num_returns == -1
                    and spec.return_ids[0] not in self._stream_completion):
                # completion object already freed and the producer just finished:
                # last chance to drop the stream bookkeeping
                self._stream_counts.pop(spec.task_id, None)
                self._stream_abandoned.pop(spec.task_id, None)
                self._stream_cancel_sent.discard(spec.task_id)
        self._schedule()

    # -- maintenance: spilling + memory monitor ----------------------------------------
    def _maintenance_loop(self) -> None:
        interval = max(0.05, self.memory_monitor_refresh_ms / 1000.0)
        while not self._shutdown:
            if self._maint_wakeup.wait(interval):
                break  # shutdown
            for check in (self._check_spill, self._check_memory_pressure,
                          self._check_agent_health, self._check_stuck_starting):
                try:
                    check()
                except Exception as e:
                    # a monitor that silently stops firing means spilling/OOM
                    # protection is off — one throttled line per 30s per check
                    if self._maint_warn.ready(check.__name__):
                        import logging as _logging

                        _logging.getLogger("ray_tpu.node").warning(
                            "maintenance check %s failed (suppressed 30s): %r",
                            check.__name__, e)

    def _check_stuck_starting(self) -> None:
        """Kill workers that never complete the spawn handshake (reference
        worker_register_timeout_seconds): a wedged interpreter in "starting"
        would otherwise hold a pool slot forever."""
        timeout = _worker_start_timeout()
        now = time.time()
        with self._lock:
            stuck = [w for n in self._nodes.values() for w in n.workers.values()
                     if w.state == "starting" and now - w.started_at > timeout]
        for w in stuck:
            try:
                w.process.kill()  # death-cleanup path handles bookkeeping
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    def _check_agent_health(self) -> None:
        """Heartbeat-based agent failure detection (reference
        GcsHealthCheckManager, gcs_health_check_manager.h:45). Connection EOF is
        the fast path; this catches hosts that hang without closing the socket."""
        timeout = CONFIG.agent_heartbeat_timeout_s
        now = time.time()
        # outage-aware boot grace: right after a head (re)start, agents that
        # were healthy through the outage are still redialing/reattaching —
        # reaping them now would turn a survivable restart into a mass
        # node-death event. Heartbeat reaping arms once the grace passes.
        if now - self._boot_at < max(timeout, CONFIG.head_restart_grace_s):
            return
        with self._lock:
            stale = [a for a in self._agent_conns.values()
                     if now - a.last_heartbeat > timeout]
        for agent in stale:
            try:
                agent.conn.close()  # ends the gRPC stream; reader fires death too
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            self._on_agent_death(agent)

    def _check_spill(self) -> None:
        """Spill LRU objects to disk when shared memory passes the high watermark
        (reference LocalObjectManager + plasma eviction pressure)."""
        cap = self._object_store_capacity
        if not cap:
            return
        used = self.store.memory_bytes()
        if used > self.spill_threshold * cap:
            target = int(self.spill_target * cap)
            self.store.spill_lru(used - target, self.spill_dir)

    def _check_memory_pressure(self) -> None:
        """OOM guard: above the usage threshold, kill the most recently started
        retriable task's worker (reference worker_killing_policy_retriable_fifo.h)."""
        if self.memory_usage_threshold >= 1.0:
            return
        frac = self._memory_sampler()
        if frac is None or frac < self.memory_usage_threshold:
            return
        victim = None
        with self._lock:
            running = []
            for n in self._nodes.values():
                for w in n.workers.values():
                    if w.state != "busy" or not w.inflight or w.actor_id is not None:
                        continue
                    ts = self.tasks.get(w.inflight[0])
                    if ts is None or ts.spec.kind != "task":
                        continue
                    running.append((ts.dispatched_at or 0.0, ts.spec, w))
            # prefer retriable tasks, newest first (retriable-FIFO policy)
            retriable = [r for r in running if r[1].attempt < r[1].max_retries]
            pool = retriable or running
            if pool:
                victim = max(pool, key=lambda p: p[0])[2]
        if victim is not None:
            self.num_oom_kills += 1
            self._kill_worker(victim, OutOfMemoryError(
                f"worker killed by memory monitor (usage {frac:.0%} >= "
                f"{self.memory_usage_threshold:.0%})"))

    # -- lineage reconstruction --------------------------------------------------------
    def _on_object_spilled(self, oid: ObjectID, old_loc) -> None:
        """spill_lru moved a head-local object to disk: adopted same-host-map
        replicas (pull_to_store shared the head's mapping instead of copying)
        cache old_loc verbatim and now point at a deleted arena entry /
        unlinked segment — drop them so the next use re-transfers from the
        spilled primary instead of raising ObjectLost. Physical replica copies
        live at their own locations and are untouched."""
        with self._transfer_lock:
            for key in [k for k, v in self._replicas.items()
                        if k[0] == oid and v == old_loc]:
                self._replicas.pop(key, None)

    def _on_object_freed(self, oid: ObjectID) -> None:
        """Drop the lineage entry, release its argument pins, free replicas."""
        with self._lock:
            task_id = self._stream_completion.pop(oid, None)
            if task_id is not None:
                if task_id in self.tasks:
                    # producer still running with no possible consumer left:
                    # drop every item it yields from here on (already-yielded
                    # refs own their items and decref themselves)
                    self._stream_abandoned[task_id] = self._stream_counts.get(task_id, 0)
                else:
                    self._stream_counts.pop(task_id, None)
                    self._stream_abandoned.pop(task_id, None)
                    self._stream_cancel_sent.discard(task_id)
        spec = self.lineage.pop(oid, None)
        if spec is not None:
            for arg in spec.arg_refs:
                self.store.decref(arg)
        with self._transfer_lock:
            replicas = [(host, self._replicas.pop((o, host)))
                        for (o, host) in list(self._replicas)
                        if o == oid]
        for host, loc in replicas:
            if host == "local":
                object_store.free_local(loc)
            else:
                agent = self._agents_by_key.get(host)
                if agent is not None:
                    try:
                        agent.send(("free_object", loc))
                    # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                    except Exception:
                        pass

    def _recover_object(self, oid: ObjectID):
        """Return a (possibly re-created) location for oid. If the stored location
        is gone, resubmit the creating task from lineage (reference
        ObjectRecoveryManager::RecoverObject). Concurrent recoveries of the same
        object dedup onto one resubmission."""
        loc = self.store.try_location(oid)
        if loc is not None and self._location_alive(loc):
            return loc
        spec = self.lineage.get(oid)
        if spec is None:
            raise object_store.ObjectLost(
                f"object {oid.hex()[:12]} is lost and has no lineage to reconstruct")
        with self._lock:
            running = any(t.spec.task_id == spec.task_id for t in self.tasks.values())
            resubmit = not running and not (set(spec.return_ids) & self._recovering)
            if resubmit:
                self._recovering.update(spec.return_ids)
                # drop the dead locations under the SAME lock: a concurrent
                # recoverer that loses the resubmit race must block in
                # store.location() below until reconstruction re-adds a live
                # location — never read the stale dead entry and return it
                for out_oid in spec.return_ids:
                    self.store.drop_location(out_oid)
        try:
            if resubmit:
                respec = copy.copy(spec)
                respec.attempt = 0
                respec.task_id = TaskID.generate()
                respec.arg_refs = list(spec.arg_refs)
                self.submit(respec)
                # rebalance submit's extra incref: existing ObjectRefs already hold one
                for out_oid in respec.return_ids:
                    self.store.decref(out_oid)
            return self.store.location(
                oid, timeout=CONFIG.object_location_timeout_s)
        finally:
            if resubmit:
                with self._lock:
                    self._recovering.difference_update(spec.return_ids)

    def _location_alive(self, loc) -> bool:
        kind = loc[0]
        if kind == "remote":
            agent = self._agents_by_key.get(loc[1])
            return agent is not None and agent.alive
        try:
            if kind == "arena":
                arena = object_store._open_arena(loc[1])
                view = arena.get(loc[2])
                if view is None:
                    return False
                view.release()
                arena.unpin(loc[2])
                return True
            if kind == "shm":
                from multiprocessing import shared_memory

                seg = shared_memory.SharedMemory(name=loc[1])
                seg.close()
                return True
            if kind == "disk":
                return os.path.exists(loc[1])
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:
            return False
        return True  # inline is always alive

    def dump_worker_stacks(self, timeout_s: float = 5.0) -> Dict[str, str]:
        """Thread stacks of every live worker + this coordinator process
        (reference: py-spy dumps via the dashboard reporter module)."""
        from .worker import _format_thread_stacks

        token = os.urandom(8).hex()
        with self._lock:
            workers = [w for n in self._nodes.values() for w in n.workers.values()
                       if w.state not in ("dead", "starting")]
            self._stack_dumps[token] = {}
        sent = 0
        for w in workers:
            try:
                w.send(("dump_stacks", token))
                sent += 1
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass  # dead pipe: don't wait on a reply that can never come
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if len(self._stack_dumps.get(token, {})) >= sent:
                    break
            time.sleep(0.05)
        with self._lock:
            out = dict(self._stack_dumps.pop(token, {}))
        out["driver"] = _format_thread_stacks()
        return out

    def profile_workers(self, duration_s: float = 2.0, hz: float = 100.0,
                        grace_s: float = 5.0) -> Dict[str, Dict[str, int]]:
        """Sampling profile of every live worker + the driver: each process
        samples its own threads for duration_s at hz and returns collapsed
        stacks (reference: `py-spy record` through the dashboard reporter
        module; here the workers self-sample over the control pipe)."""
        from .worker import _sample_collapsed_stacks

        token = os.urandom(8).hex()
        with self._lock:
            workers = [w for n in self._nodes.values() for w in n.workers.values()
                       if w.state not in ("dead", "starting")]
            self._stack_dumps[token] = {}
        sent = 0
        for w in workers:
            try:
                w.send(("profile", token, duration_s, hz))
                sent += 1
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
        # the driver samples itself while the workers sample themselves
        driver = _sample_collapsed_stacks(duration_s, hz)
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            with self._lock:
                if len(self._stack_dumps.get(token, {})) >= sent:
                    break
            time.sleep(0.05)
        with self._lock:
            out = dict(self._stack_dumps.pop(token, {}))
        out["driver"] = driver
        return out

    def _gc_arena_after_death(self, w: Optional[WorkerHandle] = None) -> None:
        """Reclaim arena space from a dead worker: unsealed half-writes and sealed
        outputs whose result message never reached us (reference analog: plasma
        disconnect cleanup + ObjectLifecycleManager). For a remote worker the GC
        runs on its host's agent against that host's arena."""
        host = self._worker_host(w)
        with self.store._lock:
            keep = [oid.binary() for oid, loc in self.store._locations.items()
                    if self._loc_host(loc) == host]
        with self._transfer_lock:
            keep += [oid.binary() for (oid, h) in self._replicas if h == host]

        if host != "local":
            agent = self._agents_by_key.get(host)
            if agent is None or not agent.alive:
                return

            def gc_remote():
                try:
                    agent.call("gc_dead_owners", keep, timeout=30.0)
                # graftlint: allow[swallowed-exception] GC hint to a possibly-dead agent; its death reaps the owners anyway
                except Exception:
                    pass

            threading.Thread(target=gc_remote, daemon=True, name="arena-gc").start()
            return
        arena = object_store._default_arena()
        if arena is None:
            return

        def gc():
            try:
                arena.gc_dead_owners(keep)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass

        threading.Thread(target=gc, daemon=True, name="arena-gc").start()

    def _drain_actor_queue_locked(self, st: ActorState) -> None:
        """Fail every pending method of a dead actor (caller holds the lock)."""
        remaining = deque()
        while self.pending:
            spec = self.pending.popleft()
            if spec.kind == "actor_method" and spec.actor_id == st.actor_id:
                self._fail_returns(spec, ActorDiedError(f"actor died: {st.death_cause!r}"))
            else:
                remaining.append(spec)
        self.pending = remaining
        self._rebuild_shape_counts()

    def _fail_returns(self, spec: TaskSpec, err: Exception) -> None:
        wrapped = err if isinstance(err, (TaskError, ActorDiedError, WorkerCrashedError, TaskCancelledError)) else TaskError(err, spec.name)
        for oid in spec.return_ids:
            self.store.mark_failed(oid, wrapped)
        self.tasks.pop(spec.task_id, None)
        self._unpin_args(spec)

    def _unpin_args(self, spec: TaskSpec) -> None:
        for oid in spec.arg_refs:
            self.store.decref(oid)
        spec.arg_refs = []

    def _on_worker_death(self, w: WorkerHandle, err: Optional[Exception] = None) -> None:
        with self._lock:
            if w.state == "dead":
                return
            w.state = "dead"
            if w.actor_id is not None:
                # close the dispatch window NOW, under the same lock: a submit
                # racing this death must queue (state != alive), not send into
                # the dying pipe and hang forever. _on_actor_worker_death below
                # settles the final state (restarting or dead).
                st = self.actors.get(w.actor_id)
                if st is not None and st.state == "alive":
                    st.state = "restarting"
                    st.worker = None
            self._conns.pop(w.conn, None)
            if isinstance(w, RemoteWorkerHandle):
                w.agent.workers.pop(w.worker_id.hex(), None)
            w.node.workers.pop(w.worker_id, None)
            # env-keyed workers idle under pool_key, not accel — removing by
            # accel left dead handles in env pools (benign: pop_idle skips
            # dead, but the handles pinned memory until popped)
            pool = w.node.idle.get(w.pool_key or w.accel)
            if pool and w in pool:
                pool.remove(w)
            inflight = list(w.inflight)
            w.inflight.clear()
            if w.resources_held:
                (w.bundle_ledger or w.node.ledger).release(w.resources_held)
                w.resources_held = {}
            self.metrics_by_worker.pop(w.worker_id, None)
        self._gc_arena_after_death(w)
        if err is None:
            err = WorkerCrashedError(f"worker {w.worker_id.hex()[:8]} died unexpectedly")
        for task_id in inflight:
            ts = self.tasks.get(task_id)
            if ts is None:
                continue
            spec = ts.spec
            if ts.cancelled:
                self._fail_returns(spec, TaskCancelledError(f"task {spec.name} cancelled"))
            elif spec.attempt < spec.max_retries and spec.kind == "task":
                spec.attempt += 1
                with self._lock:
                    self._pending_append(spec)
            else:
                self._fail_returns(spec, err)
        if w.actor_id is not None:
            self._on_actor_worker_death(w.actor_id, err)
        self._abort_collective_memberships(w, err)
        self._schedule()

    def _abort_collective_memberships(self, w: WorkerHandle, err: Exception) -> None:
        """Declare a dead worker's collective ranks failed: poison each joined
        group's coordinator so surviving ranks fail fast with
        CollectiveAbortError (reference: NCCL comm abort on peer death) within
        one abort-poll interval rather than at collective_op_timeout_s. The
        epoch scopes the abort — a late death notice for a rank of an already
        re-initialized group is rejected by the coordinator, not the board."""
        dead: List[Tuple[str, int, int]] = []
        with self._lock:
            for group, members in list(self._collective_members.items()):
                for rank, (wh, epoch) in list(members.items()):
                    if wh is w:
                        dead.append((group, rank, epoch))
                        members.pop(rank, None)
                if not members:
                    self._collective_members.pop(group, None)
        counted_groups = set()
        for group, rank, epoch in dead:
            # the head is the failure authority, so the abort counter + the
            # timeline event live here: one increment per poisoned GROUP (a
            # worker holding several ranks of one group dies once), not one
            # per rank entry or per surviving observer
            if group not in counted_groups:
                counted_groups.add(group)
                try:
                    from ray_tpu.util import telemetry as _tel

                    _tel.get_counter(
                        "collective_aborts_total",
                        "collective groups poisoned after a rank death",
                        tag_keys=("group",)).inc(1.0, tags={"group": group})
                    _tel.event("collective.abort", "collective", group=group,
                               epoch=epoch, failed_rank=rank,
                               reason=f"worker {w.worker_id.hex()[:8]} died")
                # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the data path down
                except Exception:
                    pass
            try:
                coord = self.get_named_actor_handle(
                    f"coordinator.{group}", "ray_tpu.collective")
                coord.abort.remote(
                    f"rank {rank} (worker {w.worker_id.hex()[:8]}) died: {err}",
                    rank, epoch)
            # graftlint: allow[swallowed-exception] coordinator died with the worker: survivors still fail fast via ActorDiedError on poll
            except Exception:
                # coordinator gone (it may have lived on this very worker):
                # survivors still fail fast — their polls hit ActorDiedError,
                # which the client loop converts to CollectiveAbortError
                pass

    def _on_actor_worker_death(self, actor_id: ActorID, err: Exception) -> None:
        with self._lock:
            st = self.actors.get(actor_id)
            if st is None or st.state == "dead":
                return
            spec = st.creation_spec
            if st.restarts_used < spec.max_restarts or spec.max_restarts == -1:
                st.restarts_used += 1
                st.state = "restarting"
                st.worker = None
                respawn = TaskSpec(**{**spec.__dict__})
                respawn.task_id = TaskID.generate()
                respawn.return_ids = [ObjectID.generate()]
                respawn.attempt = 0
                st.creation_spec = respawn
                self.tasks[respawn.task_id] = TaskState(respawn)
                self.store.incref(respawn.return_ids[0])
                self._pending_append(respawn)
            else:
                st.state = "dead"
                st.death_cause = err
                self._unjournal_actor(st)
                self._drain_actor_queue_locked(st)
                if st.name:
                    self.gcs.unregister_named_actor(st.name, st.namespace)
                if spec.max_restarts != 0:
                    self._unpin_args(spec)

    # -- streaming generators ------------------------------------------------------------
    def drop_stream(self, task_id: TaskID, start_index: int) -> None:
        """Consumer abandoned a streaming generator at start_index: release the
        unconsumed items (already-yielded refs own their items and decref via
        their own GC). Items the producer yields after this are dropped on
        registration (reference: generator ref GC releases dynamic returns)."""
        from .object_ref import stream_item_id

        w = None
        with self._lock:
            prev = self._stream_abandoned.get(task_id)
            if prev is not None and prev <= start_index:
                return
            self._stream_abandoned[task_id] = start_index
            count = self._stream_counts.get(task_id, 0)
            # cancel the producer NOW if it is dispatched somewhere — a
            # generator blocked between yields (long compute, queued engine
            # request) would otherwise hold its worker/slot until it happens
            # to yield again (the stream-item handler is only a fallback for
            # producers dispatched after this drop)
            if task_id not in self._stream_cancel_sent:
                for node in self._nodes.values():
                    for wh in node.workers.values():
                        if task_id in wh.inflight:
                            w = wh
                            break
                    if w is not None:
                        break
                if w is not None:
                    self._stream_cancel_sent.add(task_id)
        if w is not None:
            try:
                w.send(("cancel_stream", task_id))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
        for i in range(start_index, count):
            self.store.decref(stream_item_id(task_id, i))

    # -- actor management ----------------------------------------------------------------
    def kill_actor(self, actor_id: ActorID, no_restart: bool = True, from_gc: bool = False) -> None:
        with self._lock:
            st = self.actors.get(actor_id)
            if st is None:
                return
            if from_gc and st.detached:
                return
            if no_restart:
                st.creation_spec.max_restarts = st.restarts_used  # exhaust restarts
            if st.state in ("pending", "restarting"):
                st.kill_on_creation = True
                return
            w = st.worker
        if w is None:
            return
        if from_gc:
            # Graceful: the exit message queues behind already-dispatched methods.
            try:
                w.send(("exit",))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
        else:
            self._kill_worker(w, ActorDiedError("actor was killed via ray_tpu.kill()"))

    def _kill_worker(self, w: WorkerHandle, err: Exception) -> None:
        try:
            w.process.terminate()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        self._on_worker_death(w, err)

    def get_named_actor_handle(self, name: str, namespace: str = ""):
        actor_id = self.gcs.get_named_actor(name, namespace)
        if actor_id is None:
            raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
        st = self.actors.get(actor_id)
        from .actor import ActorHandle

        return ActorHandle(actor_id, st.method_meta if st else {})

    def actor_state(self, actor_id: ActorID) -> Optional[str]:
        with self._lock:
            st = self.actors.get(actor_id)
            return st.state if st else None

    # -- placement groups ---------------------------------------------------------------
    def create_placement_group(self, bundles: List[Dict[str, float]], strategy: str, name: str = "") -> PlacementGroup:
        pg = PlacementGroup(PlacementGroupID.generate(), bundles, strategy, name)
        with self._lock:
            self.pending_pgs.append(pg)
        self._schedule()
        return pg

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            self.pending_pgs = [p for p in self.pending_pgs if p.id != pg_id]
        self.pg_manager.remove(pg_id)
        self._schedule()

    # -- task cancel --------------------------------------------------------------------
    def cancel(self, oid: ObjectID, force: bool = False) -> None:
        with self._lock:
            target = None
            for task_id, ts in self.tasks.items():
                if oid in ts.spec.return_ids:
                    target = ts
                    break
            if target is None:
                return
            target.cancelled = True
            in_queue = any(s.task_id == target.spec.task_id for s in self.pending)
        if in_queue:
            self._fail_returns(target.spec, TaskCancelledError(f"task {target.spec.name} cancelled"))
        elif force and target.worker is not None and target.worker.actor_id is None:
            self._kill_worker(target.worker, TaskCancelledError("force-cancelled"))
            self._fail_returns(target.spec, TaskCancelledError(f"task {target.spec.name} cancelled"))

    # -- shutdown -----------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        with self._lock:
            agents = list(self._agent_conns.values())
        for a in agents:
            try:
                a.send(("shutdown",))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
            a.fail_all_pending("cluster shutting down")
        if self._node_listener is not None:
            try:
                self._node_listener.stop()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        if self._data_server is not None:
            self._data_server.close()
            self._data_client.close()
            self._data_server = self._data_client = None
        with self._lock:
            workers = [w for n in self._nodes.values() for w in list(n.workers.values())]
        for w in workers:
            try:
                w.send(("exit",))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for w in workers:
            t = max(0.05, deadline - time.monotonic())
            w.process.join(timeout=t)
            if w.process.is_alive():
                w.process.terminate()
        for a in agents:
            try:
                a.conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        try:
            self._wakeup_w.send_bytes(b"x")
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass
        self._router_thread.join(timeout=2.0)
        # the maintenance thread must not be mid-spill when the arena unmaps
        self._maint_wakeup.set()
        self._maint_thread.join(timeout=5.0)
        self.store.free_all()
        object_store.destroy_arena()
        self.gcs.kv.close()  # flush the persistence journal
        import shutil

        shutil.rmtree(self.spill_dir, ignore_errors=True)
        # stale spans must not leak into a future cluster's trace (util/tracing.py)
        from ray_tpu.util import tracing

        tracing.drain_local_spans()


class DriverContext:
    """Driver-side implementation of the runtime API (same surface as WorkerContext)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.node_id_hex = cluster.head_node.node_id.hex()
        self.accel = "driver"
        self._registered_fns: set = set()

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self.cluster.submit(spec)
        return [ObjectRef(oid, owned=True) for oid in spec.return_ids]

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout

        def remaining():
            return None if deadline is None else max(0.0, deadline - time.monotonic())

        # Wait for readiness sequentially (later objects are usually ready by the
        # time earlier waits finish), but pull remote-hosted bytes CONCURRENTLY —
        # N serial head-mediated transfers would cost N round-trips (reference
        # PullManager overlaps pulls the same way).
        locs: Dict[ObjectID, Tuple] = {}
        needs: List[ObjectRef] = []
        for r in ref_list:
            loc = self.cluster.store.location(r.id, remaining())
            if loc[0] == "remote":
                needs.append(r)
            else:
                locs[r.id] = loc
        if needs:
            self.cluster._pull_batch([r.id for r in needs], "local", remaining())
            for r in needs:  # replica cache is warm: these return instantly
                locs[r.id] = self.cluster._localize(r.id, "local", remaining())
        values = []
        for r in ref_list:
            try:
                values.append(object_store.resolve(locs[r.id], oid=r.id))
            except object_store.ObjectLost:
                # lineage reconstruction (reference ObjectRecoveryManager)
                self.cluster._recover_object(r.id)
                loc = self.cluster._localize(r.id, "local", 60.0)
                values.append(object_store.resolve(loc, oid=r.id))
        return values[0] if single else values

    def put(self, value) -> ObjectRef:
        oid = ObjectID.generate()
        loc = object_store.materialize(value, oid)
        self.cluster.store.add(oid, loc)
        self.cluster.store.incref(oid)
        if self.cluster.pending:
            # a queued task may have been waiting on exactly this object
            # (submits no longer run a full scheduling pass themselves)
            self.cluster._schedule()
        return ObjectRef(oid, owned=True)

    def wait(self, refs, num_returns=1, timeout=None):
        oids = [r.id for r in refs]
        ready_ids, pending_ids = self.cluster.store.wait(oids, num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    def decref(self, oid: ObjectID) -> None:
        self.cluster.store.decref(oid)

    def incref(self, oid: ObjectID) -> None:
        self.cluster.store.incref(oid)

    def drop_stream(self, task_id: TaskID, start_index: int) -> None:
        self.cluster.drop_stream(task_id, start_index)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True, from_gc: bool = False) -> None:
        self.cluster.kill_actor(actor_id, no_restart, from_gc)

    def cancel(self, oid: ObjectID, force: bool = False) -> None:
        self.cluster.cancel(oid, force)

    def get_named_actor(self, name: str, namespace: str = ""):
        return self.cluster.get_named_actor_handle(name, namespace)

    def kv_request(self, op: str, *args):
        """Internal-KV access (workers go through the pipe; drivers and the
        client server hit the GCS KV directly)."""
        return getattr(self.cluster.gcs.kv, op)(*args)

    def state_request(self, fn_name: str, *args, **kwargs):
        """State-API aggregation for remote client drivers (util/state.py)."""
        from ray_tpu.util.state import dispatch_state_request

        return dispatch_state_request(fn_name, args, kwargs)

    def push_metrics(self, snapshot: list) -> None:
        self.cluster.metrics_by_worker["driver"] = snapshot

    def push_spans(self, spans: list) -> None:
        with self.cluster._lock:
            self.cluster.trace_spans.extend(spans)

    def push_telemetry(self, batch: dict) -> None:
        from ray_tpu.util import telemetry as _tel

        with self.cluster._lock:
            self.cluster.telemetry_events.extend(
                _tel.align_batch(batch, "client-driver"))

    def push_tqdm(self, state: dict) -> None:
        from ray_tpu.experimental.tqdm_ray import _render_local

        _render_local(state)

    def register_fn(self, fn_id: bytes, fn_bytes: bytes) -> None:
        self.cluster._register_fn(fn_id, fn_bytes)

    def fn_known(self, fn_id: bytes) -> bool:
        return fn_id in self.cluster.fn_table

    def lookup_placement_group(self, pg_id):
        return self.cluster.pg_manager.lookup(pg_id)

    def pg_ready_ref(self, pg):
        return self.put(True) if pg.is_ready else self._pg_ready_async(pg)

    def _pg_ready_async(self, pg):
        oid = ObjectID.generate()
        self.cluster.store.incref(oid)

        def run():
            try:
                pg.wait(None)
                self.cluster.store.add(oid, object_store.materialize(True, oid))
            except Exception as e:  # noqa: BLE001
                self.cluster.store.mark_failed(oid, e)

        threading.Thread(target=run, daemon=True,
                         name="node-remote-put").start()
        return ObjectRef(oid, owned=True)

    def create_placement_group(self, bundles, strategy, name):
        return self.cluster.create_placement_group(bundles, strategy, name).id

    def remove_placement_group(self, pg_id):
        self.cluster.remove_placement_group(pg_id)

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="node-remote-get").start()
        return fut

    def runtime_context(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id_hex,
            "worker_id": "driver",
            "task_id": None,
            "actor_id": None,
            "accel": self.accel,
        }
