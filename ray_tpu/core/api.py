"""Public API: init/shutdown/remote/get/put/wait/kill/cancel/get_actor + cluster state.

Capability parity: reference python/ray/_private/worker.py (init:1341, get:2754, put:2890,
wait:2955, get_actor:3100, remote:3441, shutdown:1970).
"""
from __future__ import annotations

import atexit
import inspect
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from . import global_state
from .actor import ActorClass, ActorHandle
from .exceptions import GetTimeoutError
from .ids import NodeID
from .node import Cluster, DriverContext
from .object_ref import ObjectRef
from .resources import normalize_resources
from .task import RemoteFunction


def init(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    *,
    address: Optional[str] = None,
    client_server_port: Optional[int] = None,
    client_server_host: str = "127.0.0.1",  # "0.0.0.0" to accept remote drivers
    node_server_port: Optional[int] = None,  # accept node agents (multi-host head)
    node_server_host: str = "127.0.0.1",
    worker_env: Optional[Dict[str, str]] = None,
    runtime_env: Optional[Dict[str, Any]] = None,
    max_workers_per_node: Optional[int] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = True,
    **_compat,
) -> None:
    """Start the in-process cluster (head node) and connect the driver.

    address="ray-tpu://host:port" connects this process as a remote client
    driver instead (reference ray.init("ray://...") via python/ray/util/client/).
    client_server_port starts the head-side client server on that port."""
    if global_state.is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice")
    if address is not None:
        if not address.startswith(("ray-tpu://", "ray://")):
            raise ValueError(
                f"unsupported address {address!r}: use 'ray-tpu://host:port' to "
                "connect as a remote client driver, or omit address to start locally")
        from ray_tpu.util.client import connect

        if runtime_env:
            # validate BEFORE connecting; applied only after connect succeeds
            from ray_tpu.runtime_env import RuntimeEnv

            runtime_env = dict(RuntimeEnv(**runtime_env))
        ctx = connect(address.split("://", 1)[1])
        if runtime_env:
            # job-scoped default for THIS client context: every spec the driver
            # builds goes through resolved_runtime_env(), which consults the
            # active ClientContext — scoping it to the object (not os.environ)
            # keeps concurrent client contexts in one process from
            # cross-contaminating each other's job defaults (ADVICE r3)
            ctx.default_runtime_env = dict(runtime_env)
        atexit.register(shutdown)
        return
    from ray_tpu.config import CONFIG

    if num_cpus is None:
        num_cpus = (CONFIG.num_cpus if CONFIG.num_cpus is not None
                    else float(os.cpu_count() or 1))
    detected: Dict[str, float] = {}
    if num_tpus is None:
        env_tpus = CONFIG.num_tpus
        if env_tpus is not None:
            num_tpus = env_tpus
        else:
            # auto-detect TPU chips + pod-slice head resources (reference
            # TPUAcceleratorManager; core/accelerators.py)
            from .accelerators import TPUAcceleratorManager

            detected = TPUAcceleratorManager.node_resources()
            num_tpus = detected.pop("TPU", 0.0)
    total = normalize_resources(num_cpus=num_cpus, num_tpus=num_tpus, resources=resources)
    for k, v in detected.items():
        total.setdefault(k, v)
    kwargs: Dict[str, Any] = {}
    if max_workers_per_node is not None:
        kwargs["max_workers_per_node"] = max_workers_per_node
    if object_store_memory is not None:
        kwargs["object_store_memory"] = object_store_memory
    cluster = Cluster(total, worker_env=worker_env, **kwargs)
    if runtime_env:
        # job-level default (reference ray.init(runtime_env=...)): merged under
        # every task/actor runtime_env at submission; agents pre-warm pip/uv
        # overlays on join (reference per-node runtime-env agent)
        from ray_tpu.runtime_env import RuntimeEnv

        cluster.default_runtime_env = dict(RuntimeEnv(**runtime_env))
        # workers submitting nested tasks resolve the default from their env
        import json as _json

        cluster.worker_env["RAY_TPU_DEFAULT_RUNTIME_ENV"] = _json.dumps(
            cluster.default_runtime_env)
    global_state.set_cluster(cluster)
    global_state.set_worker(DriverContext(cluster))
    if node_server_port is not None:
        # this process becomes a multi-host head: remote hosts join with
        # `ray-tpu start --address=<host>:<port>` (core/node_agent.py)
        cluster.start_node_server(host=node_server_host, port=node_server_port)
    if client_server_port is not None:
        from ray_tpu.util.client.server import start_client_server

        start_client_server(host=client_server_host, port=client_server_port)
    atexit.register(shutdown)


def shutdown() -> None:
    from ray_tpu.util.client.client import ClientContext

    w = global_state.try_worker()
    if isinstance(w, ClientContext):
        w.close()
    from ray_tpu.util.client.server import stop_client_server

    stop_client_server()
    cluster = global_state.try_cluster()
    if cluster is not None:
        cluster.shutdown()
    global_state.set_cluster(None)
    global_state.set_worker(None)
    try:
        atexit.unregister(shutdown)
    # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
    except Exception:
        pass


def is_initialized() -> bool:
    return global_state.is_initialized()


def remote(*args, **options):
    """@remote decorator for functions and classes (reference worker.py:3441)."""

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    if len(args) == 1 and not options and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return wrap(args[0])
    if args:
        raise TypeError("remote() takes keyword options only, e.g. @remote(num_cpus=2)")
    return wrap


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    ctx = global_state.worker()
    try:
        return ctx.get(refs, timeout)
    except TimeoutError as e:
        raise GetTimeoutError(str(e)) from None


def put(value: Any) -> ObjectRef:
    return global_state.worker().put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return global_state.worker().wait(list(refs), num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    global_state.worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    global_state.worker().cancel(ref.id, force)


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    return global_state.worker().get_named_actor(name, namespace)


# -- cluster state ---------------------------------------------------------------------
def cluster_resources() -> Dict[str, float]:
    cluster = global_state.try_cluster()
    if cluster is None:
        return {}
    out: Dict[str, float] = {}
    for node in cluster.nodes():
        for k, v in node.ledger.total.items():
            out[k] = out.get(k, 0.0) + v
    return out


def available_resources() -> Dict[str, float]:
    cluster = global_state.try_cluster()
    if cluster is None:
        return {}
    out: Dict[str, float] = {}
    for node in cluster.nodes():
        for k, v in node.ledger.available().items():
            out[k] = out.get(k, 0.0) + v
    return out


def nodes() -> List[Dict[str, Any]]:
    if global_state.try_cluster() is None and global_state.try_worker() is None:
        return []
    from ray_tpu.util.state import gcs_nodes

    return gcs_nodes()
