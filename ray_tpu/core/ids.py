"""Unique identifiers for objects, tasks, actors, nodes, jobs, placement groups.

Capability parity: reference src/ray/common/id.h (JobID/TaskID/ObjectID/ActorID/NodeID).
We keep flat 16-byte random ids; lineage is tracked in owner tables instead of being
embedded in the id bits (simpler, and reconstruction metadata lives with the owner).
"""
from __future__ import annotations

import os
import binascii


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(f"{type(self).__name__} requires {self.SIZE} bytes")
        self._bytes = id_bytes

    @classmethod
    def generate(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(binascii.unhexlify(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class JobID(BaseID):
    SIZE = 4


class PlacementGroupID(BaseID):
    pass


class WorkerID(BaseID):
    pass
