"""Containerized worker processes for the container/image_uri runtime env.

Capability parity: reference python/ray/_private/runtime_env/image_uri.py — a
task/actor whose runtime_env names a container image runs its worker INSIDE
that image (podman there; docker or podman here, or any drop-in via
RAY_TPU_CONTAINER_RUNTIME — which is also the fake-runtime seam tests use to
record the exact invocation).

Transport: an in-container worker cannot inherit the head's multiprocessing
pipe, so the node listens on an authkey'd loopback socket and the container
(run with --network host) dials back into the SAME worker protocol
(`python -m ray_tpu.core.worker --connect host:port ...`). The session dir is
mounted so the worker shares the object-store arena and session authkey; the
ray_tpu package dir is mounted read-only and prepended to PYTHONPATH so any
image with a compatible python works without baking the framework in.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from ray_tpu.config import CONFIG


class ContainerRuntimeError(RuntimeError):
    """Container worker could not be launched (no runtime, bad spec, ...)."""


def normalize_container_spec(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """{"image": str, "run_options": [str, ...]} from the container/image_uri
    runtime_env fields; None when neither is present."""
    if not runtime_env:
        return None
    container = runtime_env.get("container")
    image_uri = runtime_env.get("image_uri")
    if container:
        if not isinstance(container, dict) or not container.get("image"):
            raise ValueError('runtime_env["container"] must be {"image": ..., '
                             '"run_options": [...]}')
        return {"image": str(container["image"]),
                "run_options": [str(o) for o in container.get("run_options") or []]}
    if image_uri:
        return {"image": str(image_uri), "run_options": []}
    return None


def find_runtime() -> Optional[str]:
    """The container launcher binary: RAY_TPU_CONTAINER_RUNTIME overrides (the
    test seam), else docker, else podman."""
    override = CONFIG.container_runtime
    if override:
        return override
    return shutil.which("docker") or shutil.which("podman")


def _package_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))


def build_run_command(runtime: str, spec: Dict[str, Any], connect_addr: str,
                      node_id_hex: str, wid_hex: str, accel: str,
                      env: Dict[str, str], authkey_hex: str,
                      session_dir: str) -> List[str]:
    pkg = _package_root()
    cmd = [runtime, "run", "--rm", "--network", "host",
           "-v", f"{session_dir}:{session_dir}",
           "-v", f"{pkg}:{pkg}:ro"]
    for k, v in {**env,
                 "RAY_TPU_WORKER_AUTHKEY": authkey_hex,
                 "PYTHONPATH": pkg + os.pathsep + env.get("PYTHONPATH", "")}.items():
        cmd += ["--env", f"{k}={v}"]
    cmd += spec["run_options"]
    cmd += [spec["image"], "python", "-m", "ray_tpu.core.worker",
            "--connect", connect_addr, "--node-id", node_id_hex,
            "--worker-id", wid_hex, "--accel", accel]
    return cmd


def launch_worker_container(spec: Dict[str, Any], connect_addr: str,
                            node_id_hex: str, wid_hex: str, accel: str,
                            env: Dict[str, str], authkey_hex: str) -> subprocess.Popen:
    runtime = find_runtime()
    if runtime is None:
        raise ContainerRuntimeError(
            "runtime_env requests a container image but no container runtime "
            "was found (need docker or podman on PATH, or "
            "RAY_TPU_CONTAINER_RUNTIME)")
    from ray_tpu.job.manager import default_session_dir

    cmd = build_run_command(runtime, spec, connect_addr, node_id_hex, wid_hex,
                            accel, env, authkey_hex, default_session_dir())
    try:
        return subprocess.Popen(cmd)
    except OSError as e:
        raise ContainerRuntimeError(f"failed to exec {runtime!r}: {e}") from e


class PopenProc:
    """mp.Process-shaped adapter over the container runtime Popen."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self.pid = proc.pid

    def is_alive(self) -> bool:
        return self._proc.poll() is None

    def terminate(self) -> None:
        try:
            self._proc.terminate()
        except OSError:
            pass

    kill = terminate

    def join(self, timeout: Optional[float] = None) -> None:
        try:
            self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass


def spawn_with_dialback(container: Dict[str, Any], node_id_hex: str,
                        wid_hex: str, accel: str, env: Dict[str, str],
                        on_attach, on_fail, timeout_s: Optional[float] = None):
    """The shared container-worker launch sequence (head node and agent):
    create an authkey'd loopback Listener, launch the image pointing back at
    it, and hand the dial-back connection to on_attach(conn) from a waiter
    thread — or on_fail(err) when the container never dials back within
    timeout_s (default: the worker-start timeout, so slow image pulls respect
    RAY_TPU_WORKER_START_TIMEOUT_S). Raises ContainerRuntimeError (listener
    closed) when the launch cannot even start. Returns a PopenProc."""
    import threading

    from multiprocessing.connection import Listener

    from ray_tpu.util.client.server import generate_authkey, load_authkey

    if timeout_s is None:
        timeout_s = CONFIG.worker_start_timeout_s
    key = load_authkey() or generate_authkey()
    listener = Listener(("127.0.0.1", 0), authkey=key)
    try:
        proc = launch_worker_container(
            container, f"127.0.0.1:{listener.address[1]}", node_id_hex,
            wid_hex, accel, env, key.hex())
    except Exception:
        listener.close()
        raise

    def _wait() -> None:
        listener._listener._socket.settimeout(timeout_s)
        try:
            conn = listener.accept()
        except Exception as e:
            try:
                proc.terminate()
            except OSError:
                pass
            on_fail(e)
            return
        finally:
            listener.close()
        on_attach(conn)

    threading.Thread(target=_wait, daemon=True,
                     name="rt-container-dialback").start()
    return PopenProc(proc)


class PendingConn:
    """Send-buffering proxy for the worker pipe until the container dials
    back: pre-attach sends buffer, attach() flushes them into the real
    connection and forwards everything after. Recv-side registration (the
    cluster/agent wait loops need a real fileno) happens at attach time."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._real = None
        self.buffered: List[bytes] = []

    def attach(self, conn) -> None:
        with self._lock:
            for data in self.buffered:
                conn.send_bytes(data)
            self.buffered.clear()
            self._real = conn

    def send_bytes(self, data: bytes) -> None:
        with self._lock:
            if self._real is not None:
                self._real.send_bytes(data)
            else:
                self.buffered.append(bytes(data))

    def close(self) -> None:
        with self._lock:
            self.buffered.clear()
            if self._real is not None:
                self._real.close()
