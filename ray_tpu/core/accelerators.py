"""Accelerator managers: TPU topology detection + visibility control.

Capability parity: reference python/ray/_private/accelerators/ — the
`AcceleratorManager` ABC (accelerator.py) and `TPUAcceleratorManager` (tpu.py:110):
chip detection, `TPU_VISIBLE_CHIPS` (tpu.py:118-122), pod-type resources like
"TPU-v5e-8-head" (tpu.py:376) so slice-spanning placement groups can reserve a
whole pod slice atomically. GPU managers are intentionally absent: no GPU
anywhere in the loop (BASELINE.md).

Detection sources, in order: explicit env overrides (TPU_ACCELERATOR_TYPE /
TPU_CHIPS_PER_HOST), the TPU runtime's env (set on GCE TPU-VMs), and finally a
live jax backend query when jax is already imported and bound to TPU.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"


@dataclass
class TPUInfo:
    chips_per_host: int
    accelerator_type: str  # e.g. "v5e-8" (slice), "" if unknown
    worker_id: int  # host index within the slice
    num_hosts: int

    @property
    def pod_head_resource(self) -> Optional[str]:
        """The reference's `TPU-{pod}-head` trick: worker 0 of a slice carries one
        unit so a slice-wide placement group anchors atomically (tpu.py:376)."""
        if self.accelerator_type and self.worker_id == 0:
            return f"TPU-{self.accelerator_type}-head"
        return None


class TPUAcceleratorManager:
    """TPU detection + resource shaping (reference tpu.py:110)."""

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if visible is not None:
            return len([c for c in visible.split(",") if c.strip() != ""])
        env_chips = os.environ.get("TPU_CHIPS_PER_HOST")
        if env_chips:
            return int(env_chips)
        # TPU-VM runtime convention: bounds like "2,2,1" = 4 chips on this host
        bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
        if bounds:
            n = 1
            for part in bounds.split(","):
                n *= int(part)
            return n
        # live jax query, only if jax is already imported and on TPU (importing jax
        # here would grab the TPU runtime as a side effect of mere detection)
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                if jax.default_backend() == "tpu":
                    return len(jax.local_devices())
            # graftlint: allow[swallowed-exception] TPU probe: any jax failure here means 'no TPUs visible'
            except Exception:
                pass
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> str:
        t = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        return t

    @staticmethod
    def detect() -> Optional[TPUInfo]:
        chips = TPUAcceleratorManager.get_current_node_num_accelerators()
        if chips <= 0:
            return None
        return TPUInfo(
            chips_per_host=chips,
            accelerator_type=TPUAcceleratorManager.get_current_node_accelerator_type(),
            worker_id=int(os.environ.get("TPU_WORKER_ID", "0")),
            num_hosts=int(os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") + 1
                          if os.environ.get("TPU_WORKER_HOSTNAMES") else 1),
        )

    @staticmethod
    def set_visible_chips(chip_ids) -> None:
        """Restrict this process to specific chips (reference TPU_VISIBLE_CHIPS)."""
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)

    @staticmethod
    def node_resources() -> Dict[str, float]:
        """Resources this node should advertise for its TPUs."""
        info = TPUAcceleratorManager.detect()
        if info is None:
            return {}
        out: Dict[str, float] = {"TPU": float(info.chips_per_host)}
        head = info.pod_head_resource
        if head:
            out[head] = 1.0
        if info.accelerator_type:
            out[f"accelerator_type:TPU-{info.accelerator_type.split('-')[0].upper()}"] = 1.0
        return out
