"""GCS: cluster metadata service — node table, KV store, named actors, pubsub.

Capability parity: reference src/ray/gcs/gcs_server/ (GcsNodeManager, GcsInternalKVManager,
GcsActorManager's named-actor registry, pubsub hub). Round-1 deployment is in-process with
thread-safe tables; the interface is kept narrow so a later out-of-process gRPC service can
slot in without changing callers.
"""
from __future__ import annotations

import fnmatch
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .ids import ActorID, NodeID


@dataclass
class NodeInfo:
    node_id: NodeID
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    start_time: float = field(default_factory=time.time)


class KVStore:
    """Namespaced key-value store (reference: GcsInternalKVManager, gcs_kv_manager.h:104).

    With a persistence path (reference: RedisStoreClient behind GcsTableStorage),
    mutations append to a journal; a fresh KVStore replays it at startup, so
    cluster-level state (serve app configs, job table, user KV) survives a
    coordinator restart the way GCS state survives via Redis."""

    def __init__(self, persistence_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, bytes], bytes] = {}
        self._journal = None
        if persistence_path:
            import os

            os.makedirs(os.path.dirname(persistence_path) or ".", exist_ok=True)
            self._replay(persistence_path)
            # compact: rewrite the journal as the current snapshot so replay cost
            # and file size track live keys, not historical mutation count
            tmp = persistence_path + ".compact"
            with open(tmp, "wb") as f:
                self._journal = f
                for (ns, k), v in self._data.items():
                    self._log("put", ns, k, v)
                self._journal = None
            os.replace(tmp, persistence_path)
            self._journal = open(persistence_path, "ab")

    def _replay(self, path: str) -> None:
        import base64
        import json
        import os

        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    k = (rec["ns"], base64.b64decode(rec["k"]))
                    if rec["op"] == "put":
                        self._data[k] = base64.b64decode(rec["v"])
                    else:
                        self._data.pop(k, None)
                except (ValueError, KeyError):
                    continue  # torn tail write from a crash: ignore

    def _log(self, op: str, namespace: str, key: bytes, value: Optional[bytes]) -> None:
        if self._journal is None:
            return
        import base64
        import json

        rec = {"op": op, "ns": namespace, "k": base64.b64encode(key).decode()}
        if value is not None:
            rec["v"] = base64.b64encode(value).decode()
        self._journal.write(json.dumps(rec).encode() + b"\n")
        self._journal.flush()

    def put(self, key: bytes, value: bytes, namespace: str = "", overwrite: bool = True) -> bool:
        with self._lock:
            k = (namespace, key)
            if not overwrite and k in self._data:
                return False
            self._data[k] = value
            self._log("put", namespace, key, value)
            return True

    def get(self, key: bytes, namespace: str = "") -> Optional[bytes]:
        with self._lock:
            return self._data.get((namespace, key))

    def delete(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            existed = self._data.pop((namespace, key), None) is not None
            if existed:
                self._log("del", namespace, key, None)
            return existed

    def exists(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return (namespace, key) in self._data

    def keys(self, prefix: bytes = b"", namespace: str = "") -> List[bytes]:
        with self._lock:
            return [k for (ns, k) in self._data if ns == namespace and k.startswith(prefix)]

    def close(self) -> None:
        with self._lock:  # serialize against in-flight put/delete journal writes
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
                self._journal = None


class PubSub:
    """Channel-based pubsub (reference: src/ray/pubsub/ long-poll publisher/subscriber)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable[[Any], None]]] = {}

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._subs.setdefault(channel, []).append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs[channel].remove(callback)
                except (KeyError, ValueError):
                    pass

        return unsubscribe

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            # Pattern subscribers: a subscription to "node:*" sees "node:added".
            cbs = []
            for ch, lst in self._subs.items():
                if ch == channel or fnmatch.fnmatch(channel, ch):
                    cbs.extend(lst)
        for cb in cbs:
            try:
                cb(message)
            except Exception:
                pass


class GCS:
    def __init__(self, persistence_path: Optional[str] = None):
        from ray_tpu.config import CONFIG

        persistence_path = persistence_path or CONFIG.gcs_persistence_path
        self.kv = KVStore(persistence_path)
        self.pubsub = PubSub()
        self._lock = threading.Lock()
        self._nodes: Dict[NodeID, NodeInfo] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}  # (namespace, name) -> id

    # -- node table ----------------------------------------------------------------
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self._nodes[info.node_id] = info
        self.pubsub.publish("node:added", info)

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info:
                info.alive = False
        if info:
            self.pubsub.publish("node:removed", info)

    def nodes(self, alive_only: bool = True) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive or not alive_only]

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    # -- named actors ---------------------------------------------------------------
    def register_named_actor(self, name: str, namespace: str, actor_id: ActorID) -> bool:
        with self._lock:
            key = (namespace, name)
            if key in self._named_actors:
                return False
            self._named_actors[key] = actor_id
            return True

    def get_named_actor(self, name: str, namespace: str) -> Optional[ActorID]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def unregister_named_actor(self, name: str, namespace: str) -> None:
        with self._lock:
            self._named_actors.pop((namespace, name), None)

    def list_named_actors(self, namespace: Optional[str] = None) -> List[Tuple[str, str]]:
        with self._lock:
            return [
                (ns, name)
                for (ns, name) in self._named_actors
                if namespace is None or ns == namespace
            ]
