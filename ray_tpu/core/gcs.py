"""GCS: cluster metadata service — node table, KV store, named actors, pubsub.

Capability parity: reference src/ray/gcs/gcs_server/ (GcsNodeManager, GcsInternalKVManager,
GcsActorManager's named-actor registry, pubsub hub). Round-1 deployment is in-process with
thread-safe tables; the interface is kept narrow so a later out-of-process gRPC service can
slot in without changing callers.
"""
from __future__ import annotations

import fnmatch
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .ids import ActorID, NodeID


@dataclass
class NodeInfo:
    node_id: NodeID
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    start_time: float = field(default_factory=time.time)


class JournalFencedError(RuntimeError):
    """This head lost journal ownership to a newer head (split-brain fence)."""


class _UriJournal:
    """Append-log over an fsspec URI "directory": each flush writes a new
    numbered segment object, replay reads segments in order, startup compaction
    collapses them into one snapshot segment and deletes the rest.

    This is the EXTERNAL-store half of head HA (reference: RedisStoreClient,
    gcs_redis_failure_detector.h): with the journal in a bucket, a replacement
    head on a *different machine/port* replays the same state. Per-mutation
    segment writes trade object-store round-trip latency for durability — the
    same trade Redis AOF fsync=always makes; cluster-metadata mutation rates
    (app configs, named actors, job table) are low.

    Split-brain protection (ADVICE r4): segment names embed a per-writer token,
    so two heads racing the same URI can never overwrite each other's segments
    (names cannot collide); and each writer claims an ``owner`` marker at
    startup — the marker is newest-writer-wins, and an old head discovers it
    lost ownership (checked before compaction and every owner_check_every
    appends) and stops journaling with JournalFencedError rather than keep
    interleaving state with the replacement. There is no distributed lock here
    — the operator contract is still one INTENDED writer per URI; the fence
    turns an accidental second writer from silent corruption into a loud stop."""

    def __init__(self, uri: str):
        import secrets

        from ray_tpu.config import CONFIG
        from ray_tpu.train import storage

        self.owner_check_every = int(CONFIG.gcs_owner_check_every)
        self._storage = storage
        self.uri = uri.rstrip("/")
        self.seq = 0
        self.token = secrets.token_hex(8)
        self._appends_since_check = 0
        # newest-writer-wins claim; heads that wrote before us are fenced out
        self._storage.write_bytes(f"{self.uri}/owner", self.token.encode())

    def _check_owner(self) -> None:
        cur = self._storage.read_bytes(f"{self.uri}/owner")
        if cur is not None and cur.decode(errors="replace") != self.token:
            raise JournalFencedError(
                f"journal {self.uri} is now owned by writer {cur!r} — this "
                "head lost a failover race and must stop journaling")
        self._appends_since_check = 0

    def _segments(self) -> List[str]:
        return sorted(n for n in self._storage.listdir(self.uri)
                      if n.startswith("seg-"))

    def replay_lines(self):
        segs = self._segments()
        for name in segs:
            data = self._storage.read_bytes(f"{self.uri}/{name}") or b""
            yield from data.splitlines()
        if segs:
            # name = seg-{seq:012d}[-{token}]; tokens keep names collision-free
            self.seq = int(segs[-1][4:16]) + 1

    def append(self, line: bytes) -> None:
        self._appends_since_check += 1
        if self._appends_since_check >= self.owner_check_every:
            self._check_owner()
        self._storage.write_bytes(
            f"{self.uri}/seg-{self.seq:012d}-{self.token}", line)
        self.seq += 1

    def compact(self, lines: List[bytes]) -> None:
        self._check_owner()  # never delete segments we may no longer own
        old = self._segments()
        self.append(b"\n".join(lines))
        for name in old:
            self._storage.delete(f"{self.uri}/{name}")

    def close(self) -> None:
        pass


class KVStore:
    """Namespaced key-value store (reference: GcsInternalKVManager, gcs_kv_manager.h:104).

    With a persistence path (reference: RedisStoreClient behind GcsTableStorage),
    mutations append to a journal; a fresh KVStore replays it at startup, so
    cluster-level state (serve app configs, job table, user KV) survives a
    coordinator restart the way GCS state survives via Redis. A local file path
    journals to that file; a URI (``gs://bucket/cluster1/gcs``, or ``mock://``
    in tests) journals to an external store, so the replacement head can start
    on a DIFFERENT machine or port."""

    def __init__(self, persistence_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, bytes], bytes] = {}
        self._journal = None
        self._uri_journal: Optional[_UriJournal] = None
        if persistence_path and "://" in persistence_path:
            self._uri_journal = _UriJournal(persistence_path)
            for line in self._uri_journal.replay_lines():
                self._apply_line(line)
            self._uri_journal.compact(
                [self._encode("put", ns, k, v) for (ns, k), v in self._data.items()])
        elif persistence_path:
            import os

            os.makedirs(os.path.dirname(persistence_path) or ".", exist_ok=True)
            self._replay(persistence_path)
            # compact: rewrite the journal as the current snapshot so replay cost
            # and file size track live keys, not historical mutation count
            tmp = persistence_path + ".compact"
            with open(tmp, "wb") as f:
                self._journal = f
                for (ns, k), v in self._data.items():
                    self._log("put", ns, k, v)
                self._journal = None
            os.replace(tmp, persistence_path)
            self._journal = open(persistence_path, "ab")

    def _apply_line(self, line: bytes) -> None:
        import base64
        import json

        try:
            rec = json.loads(line)
            k = (rec["ns"], base64.b64decode(rec["k"]))
            if rec["op"] == "put":
                self._data[k] = base64.b64decode(rec["v"])
            else:
                self._data.pop(k, None)
        except (ValueError, KeyError):
            pass  # torn tail write from a crash: ignore

    def _replay(self, path: str) -> None:
        import os

        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for line in f:
                self._apply_line(line)

    @staticmethod
    def _encode(op: str, namespace: str, key: bytes, value: Optional[bytes]) -> bytes:
        import base64
        import json

        rec = {"op": op, "ns": namespace, "k": base64.b64encode(key).decode()}
        if value is not None:
            rec["v"] = base64.b64encode(value).decode()
        return json.dumps(rec).encode()

    def _log(self, op: str, namespace: str, key: bytes, value: Optional[bytes]) -> None:
        if self._uri_journal is not None:
            self._uri_journal.append(self._encode(op, namespace, key, value))
            return
        if self._journal is None:
            return
        self._journal.write(self._encode(op, namespace, key, value) + b"\n")
        self._journal.flush()

    def put(self, key: bytes, value: bytes, namespace: str = "", overwrite: bool = True) -> bool:
        with self._lock:
            k = (namespace, key)
            if not overwrite and k in self._data:
                return False
            self._data[k] = value
            self._log("put", namespace, key, value)
            return True

    def get(self, key: bytes, namespace: str = "") -> Optional[bytes]:
        with self._lock:
            return self._data.get((namespace, key))

    def delete(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            existed = self._data.pop((namespace, key), None) is not None
            if existed:
                self._log("del", namespace, key, None)
            return existed

    def exists(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return (namespace, key) in self._data

    def keys(self, prefix: bytes = b"", namespace: str = "") -> List[bytes]:
        with self._lock:
            return [k for (ns, k) in self._data if ns == namespace and k.startswith(prefix)]

    def close(self) -> None:
        with self._lock:  # serialize against in-flight put/delete journal writes
            if self._journal is not None:
                try:
                    self._journal.close()
                except OSError:
                    pass
                self._journal = None


class PubSub:
    """Channel-based pubsub (reference: src/ray/pubsub/ long-poll publisher/subscriber)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable[[Any], None]]] = {}

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._subs.setdefault(channel, []).append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs[channel].remove(callback)
                except (KeyError, ValueError):
                    pass

        return unsubscribe

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            # Pattern subscribers: a subscription to "node:*" sees "node:added".
            cbs = []
            for ch, lst in self._subs.items():
                if ch == channel or fnmatch.fnmatch(channel, ch):
                    cbs.extend(lst)
        for cb in cbs:
            try:
                cb(message)
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass


class GCS:
    def __init__(self, persistence_path: Optional[str] = None):
        from ray_tpu.config import CONFIG

        persistence_path = persistence_path or CONFIG.gcs_persistence_path
        self.kv = KVStore(persistence_path)
        self.pubsub = PubSub()
        self._lock = threading.Lock()
        self._nodes: Dict[NodeID, NodeInfo] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}  # (namespace, name) -> id

    # -- node table ----------------------------------------------------------------
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self._nodes[info.node_id] = info
        self.pubsub.publish("node:added", info)

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info:
                info.alive = False
        if info:
            self.pubsub.publish("node:removed", info)

    def nodes(self, alive_only: bool = True) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive or not alive_only]

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    # -- named actors ---------------------------------------------------------------
    def register_named_actor(self, name: str, namespace: str, actor_id: ActorID) -> bool:
        with self._lock:
            key = (namespace, name)
            if key in self._named_actors:
                return False
            self._named_actors[key] = actor_id
            return True

    def get_named_actor(self, name: str, namespace: str) -> Optional[ActorID]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def unregister_named_actor(self, name: str, namespace: str) -> None:
        with self._lock:
            self._named_actors.pop((namespace, name), None)

    def list_named_actors(self, namespace: Optional[str] = None) -> List[Tuple[str, str]]:
        with self._lock:
            return [
                (ns, name)
                for (ns, name) in self._named_actors
                if namespace is None or ns == namespace
            ]
