"""Cluster TLS: self-signed cert generation + loading.

Capability parity: reference python/ray/_private/tls_utils.py:6 (RAY_USE_TLS,
RAY_TLS_SERVER_CERT/KEY/CA_CERT). When enabled, the inter-NODE planes run mTLS
with one shared credential set: the head<->agent gRPC channel, the bulk data
plane, and the device-plane arm server; plaintext peers are refused at the
handshake. NOT yet covered: the ray-tpu:// client-driver port and the serve
HTTP/gRPC ingress (front those with a TLS-terminating proxy, or keep the
client port on localhost/an SSH tunnel — same posture as the reference
dashboard). The PJRT transfer-server payload stream is runtime-managed and
rides the trust of the arm handshake that gates every pull uuid.

`ray-tpu tls-init <dir>` (or generate_self_signed_tls()) mints a CA plus one
cluster certificate whose SAN covers localhost and this host's addresses;
distribute the three files to every node and set:
    RAY_TPU_USE_TLS=1
    RAY_TPU_TLS_CA=<dir>/ca.crt
    RAY_TPU_TLS_CERT=<dir>/cluster.crt
    RAY_TPU_TLS_KEY=<dir>/cluster.key
"""
from __future__ import annotations

import datetime
import ipaddress
import os
import socket
from typing import Optional, Tuple

from ray_tpu.config import CONFIG

# gRPC target-name override: clients dial by IP, the cert carries this name.
TLS_TARGET_NAME = "ray-tpu-cluster"


def use_tls() -> bool:
    return bool(CONFIG.use_tls)


def generate_self_signed_tls(out_dir: str, extra_sans: Tuple[str, ...] = ()) -> dict:
    """Mint ca.crt/ca.key + cluster.crt/cluster.key under out_dir; returns paths."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _write_key(key, path):
        with open(os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600),
                  "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption()))

    ca_key = _key()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "ray-tpu-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    sans = [x509.DNSName(TLS_TARGET_NAME), x509.DNSName("localhost")]
    ips = {"127.0.0.1"}
    try:
        ips.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ips.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    for extra in extra_sans:
        try:
            ips.add(str(ipaddress.ip_address(extra)))
        except ValueError:
            sans.append(x509.DNSName(extra))
    for ip in sorted(ips):
        sans.append(x509.IPAddress(ipaddress.ip_address(ip)))

    key = _key()
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, TLS_TARGET_NAME)]))
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    # ca.key lives in its own subdirectory: operators distribute out_dir to
    # every node (ca.crt/cluster.crt/cluster.key are all a node needs), and a
    # wholesale copy must not hand every node the power to mint valid cluster
    # certs (ADVICE r4).
    ca_priv_dir = os.path.join(out_dir, "ca-private")
    os.makedirs(ca_priv_dir, exist_ok=True)
    os.chmod(ca_priv_dir, 0o700)
    paths = {
        "ca": os.path.join(out_dir, "ca.crt"),
        "ca_key": os.path.join(ca_priv_dir, "ca.key"),
        "cert": os.path.join(out_dir, "cluster.crt"),
        "key": os.path.join(out_dir, "cluster.key"),
    }
    with open(paths["ca"], "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    _write_key(ca_key, paths["ca_key"])
    with open(paths["cert"], "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    _write_key(key, paths["key"])
    return paths


def load_cert_paths() -> Tuple[str, str, str]:
    """(ca, cert, key) file paths from config; raises if TLS is on but unset."""
    ca, cert, key = CONFIG.tls_ca, CONFIG.tls_cert, CONFIG.tls_key
    missing = [n for n, v in (("RAY_TPU_TLS_CA", ca), ("RAY_TPU_TLS_CERT", cert),
                              ("RAY_TPU_TLS_KEY", key)) if not v]
    if missing:
        raise RuntimeError(
            f"RAY_TPU_USE_TLS=1 but {', '.join(missing)} unset — run "
            "`ray-tpu tls-init <dir>` and point the env vars at its output")
    return ca, cert, key


def load_cert_bytes() -> Tuple[bytes, bytes, bytes]:
    ca, cert, key = load_cert_paths()
    with open(ca, "rb") as f:
        ca_b = f.read()
    with open(cert, "rb") as f:
        cert_b = f.read()
    with open(key, "rb") as f:
        key_b = f.read()
    return ca_b, cert_b, key_b


def server_ssl_context():
    """mTLS server context for raw-socket planes (data plane, device plane)."""
    import ssl

    ca, cert, key = load_cert_paths()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(ca)
    ctx.verify_mode = ssl.CERT_REQUIRED  # mTLS: plaintext/unknown peers refused
    return ctx


def client_ssl_context():
    import ssl

    ca, cert, key = load_cert_paths()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(ca)
    ctx.check_hostname = False  # peers dial by IP; the CA pin is the trust root
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def ingress_ssl_context():
    """Server-side-TLS context for the serve HTTP ingress: external clients
    verify the cluster cert against ca.crt but present no client cert (they
    are end users, not cluster nodes — unlike the mTLS inter-node planes)."""
    import ssl

    _ca, cert, key = load_cert_paths()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    return ctx


def ingress_grpc_credentials():
    """Server-side-TLS credentials for the serve gRPC ingress (no client-cert
    requirement)."""
    import grpc

    _ca_b, cert_b, key_b = load_cert_bytes()
    return grpc.ssl_server_credentials([(key_b, cert_b)],
                                       require_client_auth=False)


def grpc_server_credentials():
    import grpc

    ca_b, cert_b, key_b = load_cert_bytes()
    return grpc.ssl_server_credentials(
        [(key_b, cert_b)], root_certificates=ca_b,
        require_client_auth=True)


def grpc_channel_credentials():
    import grpc

    ca_b, cert_b, key_b = load_cert_bytes()
    return grpc.ssl_channel_credentials(
        root_certificates=ca_b, private_key=key_b, certificate_chain=cert_b)
