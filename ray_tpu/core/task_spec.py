"""TaskSpec and scheduling strategies.

Capability parity: reference TaskSpecification (src/ray/common/task/) and
python/ray/util/scheduling_strategies.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any  # PlacementGroup handle
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str  # hex
    soft: bool = False


@dataclass
class SpreadSchedulingStrategy:
    pass


# -- label match expressions (reference python/ray/util/scheduling_strategies.py:135:
#    NodeLabelSchedulingStrategy with In/NotIn/Exists/DoesNotExist terms) ------------

@dataclass
class In:
    values: tuple

    def __init__(self, *values: str):
        object.__setattr__(self, "values", tuple(values))

    def matches(self, present: bool, value) -> bool:
        return present and value in self.values


@dataclass
class NotIn:
    values: tuple

    def __init__(self, *values: str):
        object.__setattr__(self, "values", tuple(values))

    def matches(self, present: bool, value) -> bool:
        # an absent label trivially is "not in" the given values
        return not present or value not in self.values


@dataclass
class Exists:
    def matches(self, present: bool, value) -> bool:
        return present


@dataclass
class DoesNotExist:
    def matches(self, present: bool, value) -> bool:
        return not present


@dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto nodes whose labels satisfy every `hard` expression,
    preferring nodes that also satisfy `soft` (reference
    scheduling_strategies.py:135). No hard match -> the task waits (a node
    with the label may join later)."""

    hard: Optional[Dict[str, Any]] = None
    soft: Optional[Dict[str, Any]] = None

    @staticmethod
    def _match(exprs: Optional[Dict[str, Any]], labels: Dict[str, str]) -> bool:
        for key, expr in (exprs or {}).items():
            if not expr.matches(key in labels, labels.get(key)):
                return False
        return True

    def hard_match(self, labels: Dict[str, str]) -> bool:
        return self._match(self.hard, labels)

    def soft_match(self, labels: Dict[str, str]) -> bool:
        return self._match(self.soft, labels)


# "DEFAULT" | "SPREAD" | NodeAffinitySchedulingStrategy | PlacementGroupSchedulingStrategy
SchedulingStrategyT = Any


@dataclass
class TaskSpec:
    task_id: TaskID
    kind: str  # "task" | "actor_creation" | "actor_method"
    fn_id: bytes  # content hash of the serialized callable / class
    fn_bytes: Optional[bytes]  # cloudpickled callable; None if receiver has it cached
    name: str
    args_meta: bytes  # cloudpickled (args, kwargs) with top-level refs as _RefMarker
    arg_refs: List[ObjectID]  # top-level ObjectRef args, resolved before dispatch
    num_returns: int
    return_ids: List[ObjectID]
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategyT = "DEFAULT"
    max_retries: int = 0
    retry_exceptions: bool = False
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    max_restarts: int = 0  # actor creation only
    actor_name: Optional[str] = None
    actor_namespace: str = ""
    runtime_env: Optional[Dict[str, Any]] = None
    # actor-creation control plane (not part of the user-facing runtime_env):
    method_meta: Dict[str, Any] = field(default_factory=dict)
    detached: bool = False
    max_concurrency: int = 1
    # named concurrency groups (reference ConcurrencyGroupManager,
    # src/ray/core_worker/transport/concurrency_group_manager.h): group name ->
    # thread count (0 = thread-per-call). actor_creation carries the table;
    # actor_method may override its group per-call.
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: str = ""
    # tracing context propagation (util/tracing.py; reference: TaskSpec-embedded
    # otel context in tracing_helper.py)
    trace_ctx: Optional[Dict[str, str]] = None
    # Filled by the scheduler:
    node_id: Optional[NodeID] = None
    pg_id: Optional[PlacementGroupID] = None
    pg_bundle_index: int = -1
    attempt: int = 0


@dataclass
class _RefMarker:
    """Placeholder inside args_meta for a top-level ObjectRef argument."""

    index: int
