"""Worker process: task-execution loop + upcall channel back to the node service.

Capability parity: reference CoreWorker task execution loop
(src/ray/core_worker/core_worker.cc ExecuteTask:3298, _raylet.pyx task_execution_handler:2318)
and python/ray/_private/workers/default_worker.py. One process per worker; a duplex pipe to
the node service carries task dispatch downstream and submissions/gets/puts upstream, so
nested tasks and ray_tpu.get() inside tasks work exactly like the reference.

Accelerator isolation: workers are spawned with an `accel` tag. "cpu" workers set
JAX_PLATFORMS=cpu before anything imports jax so they never grab the TPU chip; "tpu"
workers leave platform selection alone (they own the chip while scheduled, enforced by the
TPU resource ledger — reference analog: TPU_VISIBLE_CHIPS in accelerators/tpu.py:118).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
import queue
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from . import global_state, object_store, serialization
from .exceptions import TaskError
from .ids import ActorID, ObjectID, TaskID, WorkerID
from .object_ref import ObjectRef
from .task_spec import TaskSpec, _RefMarker


import contextvars

_ASYNC_TASK_ID: "contextvars.ContextVar[Optional[TaskID]]" = contextvars.ContextVar(
    "rt_async_task_id", default=None)


class _ThreadPerCallExecutor:
    """Unbounded concurrency group (size 0): one daemon thread per call, so
    arbitrarily many parked calls (long-poll listeners) never exhaust a pool."""

    def __init__(self, name: str):
        self._name = name

    def submit(self, fn, *args):
        threading.Thread(target=fn, args=args, daemon=True,
                         name=f"cg-{self._name}").start()


class WorkerContext:
    """The worker-side implementation of the runtime API (get/put/submit/...)."""

    def __init__(self, conn, node_id_hex: str, worker_id_hex: str, accel: str):
        self.conn = conn
        self.node_id_hex = node_id_hex
        self.worker_id_hex = worker_id_hex
        self.accel = accel
        self._req_counter = 0
        self._req_lock = threading.Lock()
        self._reply_slots: Dict[int, list] = {}  # req_id -> [Event, ok, value]
        self._task_queue: "queue.Queue" = queue.Queue()
        self._fn_cache: Dict[bytes, Any] = {}
        self._registered_fns: set = set()
        self._send_lock = threading.Lock()
        self._recv_thread: Optional[threading.Thread] = None
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._method_pool = None
        self._group_pools: Dict[str, Any] = {}  # concurrency group -> executor
        self._method_groups: Dict[str, str] = {}  # method name -> default group
        self._async_methods: set = set()  # async def methods (per-actor event loop)
        self._actor_loop = None  # asyncio loop thread, created on demand
        # per-thread: concurrent methods of a threaded actor each track their own task
        self._task_ctx = threading.local()
        self._loop_lock = threading.Lock()  # guards _actor_loop creation
        self._cancelled_streams: set = set()  # TaskIDs whose consumer dropped the stream
        self._exit = False

    @property
    def current_task_id(self) -> Optional[TaskID]:
        # async actor methods interleave on one loop thread, so their identity
        # is context-local (each asyncio.Task owns a contextvars copy); sync
        # paths fall back to the thread-local
        async_id = _ASYNC_TASK_ID.get()
        if async_id is not None:
            return async_id
        return getattr(self._task_ctx, "task_id", None)

    @current_task_id.setter
    def current_task_id(self, value: Optional[TaskID]) -> None:
        self._task_ctx.task_id = value

    # -- transport -----------------------------------------------------------------
    def _send(self, msg) -> None:
        with self._send_lock:
            self.conn.send_bytes(cloudpickle.dumps(msg))

    def _recv(self):
        return cloudpickle.loads(self.conn.recv_bytes())

    def _next_req_id(self) -> int:
        with self._req_lock:
            self._req_counter += 1
            return self._req_counter

    def _ensure_recv_thread(self) -> None:
        """Demux thread: the ONLY reader of the pipe. Replies wake their waiting thread
        via per-request events; tasks queue for the main loop. This makes the runtime
        API safe from any thread in the worker (threaded actors: serve proxy/replicas,
        train session reporter threads, ...)."""
        if self._recv_thread is not None:
            return
        def recv_loop():
            while True:
                try:
                    msg = self._recv()
                except (EOFError, OSError):
                    self._exit = True
                    # Fail every blocked _request() waiter (any thread) — otherwise
                    # a thread inside ray_tpu.get() would hang forever when the
                    # coordinator dies without an orderly shutdown.
                    with self._req_lock:
                        slots = list(self._reply_slots.values())
                        self._reply_slots.clear()
                    err = ConnectionError("lost connection to the node coordinator")
                    for slot in slots:
                        slot[1], slot[2] = False, err
                        slot[0].set()
                    self._task_queue.put(("exit",))
                    return
                kind = msg[0]
                if kind == "reply":
                    with self._req_lock:
                        slot = self._reply_slots.pop(msg[1], None)
                    if slot is not None:
                        slot[1], slot[2] = msg[2], msg[3]
                        slot[0].set()
                    # Unmatched replies (cancelled requests) are dropped.
                elif kind == "free":
                    object_store._segment_cache.drop(msg[1])
                elif kind == "dump_stacks":
                    # one-way reply straight from the recv thread (no _request):
                    # py-spy-style introspection of a possibly-busy worker
                    try:
                        self._send(("stacks", msg[1], self.worker_id_hex,
                                    _format_thread_stacks()))
                    # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                    except Exception:
                        pass
                elif kind == "profile":
                    # py-spy-style SAMPLING profile: a detached thread samples
                    # this process for duration_s and sends collapsed stacks
                    # back (reference: py-spy record via dashboard reporter)
                    _, token, duration_s, hz = msg

                    def run_profile(token=token, duration_s=duration_s, hz=hz):
                        counts = _sample_collapsed_stacks(duration_s, hz)
                        try:
                            self._send(("stacks", token, self.worker_id_hex, counts))
                        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                        except Exception:
                            pass

                    threading.Thread(target=run_profile, daemon=True,
                                     name="rt-profiler").start()
                elif kind == "cancel_stream":
                    # consumer abandoned a streaming generator: the producing
                    # thread checks this set at every yield boundary
                    self._cancelled_streams.add(msg[1])
                elif kind == "head_restarted":
                    # the agent re-registered with a RESTARTED head: replies to
                    # requests sent on the old head are gone forever. Fail the
                    # blocked waiters typed (callers like the serve retry plane
                    # classify HeadUnavailableError and resend) instead of
                    # letting them hang on replies that will never come. The
                    # worker itself stays up — its pipe, actor state, and
                    # data-plane pulls are intact.
                    from ray_tpu.core.exceptions import HeadUnavailableError

                    with self._req_lock:
                        slots = list(self._reply_slots.values())
                        self._reply_slots.clear()
                    err = HeadUnavailableError(
                        msg[1] if len(msg) > 1 else 0.0, 0,
                        "head restarted; the pending reply was lost")
                    for slot in slots:
                        slot[1], slot[2] = False, err
                        slot[0].set()
                elif kind == "exit":
                    self._exit = True
                    self._task_queue.put(("exit",))
                else:  # task and anything main-loop-bound
                    self._task_queue.put(msg)

        self._recv_thread = threading.Thread(target=recv_loop, daemon=True, name="ray-tpu-recv")
        self._recv_thread.start()

    def _request(self, msg_type: str, *payload):
        """Send an upcall and block for its reply (thread-safe)."""
        self._ensure_recv_thread()
        req_id = self._next_req_id()
        slot = [threading.Event(), None, None]
        with self._req_lock:
            self._reply_slots[req_id] = slot
        self._send((msg_type, req_id) + payload)
        slot[0].wait()
        ok, value = slot[1], slot[2]
        if not ok:
            raise value
        return value

    # -- runtime API (mirrors DriverContext) ----------------------------------------
    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self._send(("submit", spec))
        return [ObjectRef(oid, owned=True) for oid in spec.return_ids]

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        oids = [r.id for r in ref_list]
        locs = self._request("get", oids, timeout)
        values = [self._resolve_recovering(o, loc) for o, loc in zip(oids, locs)]
        return values[0] if single else values

    def _resolve_recovering(self, oid: ObjectID, loc):
        """resolve with lineage reconstruction on loss (reference ObjectRecoveryManager)."""
        try:
            return object_store.resolve(loc, oid=oid)
        except object_store.ObjectLost:
            new_loc = self._request("recover", oid)
            return object_store.resolve(new_loc, oid=oid)

    def put(self, value) -> ObjectRef:
        oid = ObjectID.generate()
        loc = object_store.materialize(value, oid)
        self._send(("put", oid, loc))
        return ObjectRef(oid, owned=True)

    def wait(self, refs, num_returns=1, timeout=None):
        oids = [r.id for r in refs]
        ready_ids, pending_ids = self._request("wait", oids, num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    def decref(self, oid: ObjectID) -> None:
        try:
            self._send(("decref", oid))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def incref(self, oid: ObjectID) -> None:
        """Pin an object on the head node (ObjectRefGenerator.handoff: the pin
        outlives this process's refs and transfers to the adopting consumer).
        NOT best-effort: a failed pin must surface so the caller keeps relaying
        instead of handing off a stream the head may free under the adopter."""
        self._send(("incref", oid))

    def drop_stream(self, task_id: TaskID, start_index: int) -> None:
        try:
            self._send(("drop_stream", task_id, start_index))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def push_metrics(self, snapshot: list) -> None:
        """One-way metric snapshot to the coordinator (util/metrics.py)."""
        self._send(("metrics", snapshot))

    def collective_notify(self, kind: str, group_name: str, rank: int,
                          epoch: int) -> None:
        """One-way collective-membership note ("collective_join"/"collective_leave"):
        the node service keys death-triggered group aborts on these."""
        self._send((kind, group_name, rank, epoch))

    def state_request(self, fn_name: str, *args, **kwargs):
        """State-API aggregation runs on the coordinator (util/state.py)."""
        return self._request("state", fn_name, args, kwargs)

    def kv_request(self, op: str, *args):
        """Cluster KV access from a worker (reference: GCS KV over the core worker)."""
        return self._request("kv", op, *args)

    def push_spans(self, spans: list) -> None:
        """One-way trace-span batch to the coordinator (util/tracing.py)."""
        self._send(("spans", spans))

    def push_telemetry(self, batch: dict) -> None:
        """One-way telemetry event batch ({clock_offset_ns, events}) to the
        coordinator (util/telemetry.py flush thread)."""
        self._send(("telemetry", batch))

    def push_tqdm(self, state: dict) -> None:
        """One-way progress-bar state to the coordinator (experimental/tqdm_ray.py)."""
        self._send(("tqdm", state))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True, from_gc: bool = False) -> None:
        self._send(("kill_actor", actor_id, no_restart, from_gc))

    def cancel(self, oid: ObjectID, force: bool = False) -> None:
        self._send(("cancel", oid, force))

    def get_named_actor(self, name: str, namespace: str):
        return self._request("get_named_actor", name, namespace)

    def register_fn(self, fn_id: bytes, fn_bytes: bytes) -> None:
        if fn_id not in self._registered_fns:
            self._send(("register_fn", fn_id, fn_bytes))
            self._registered_fns.add(fn_id)

    def fn_known(self, fn_id: bytes) -> bool:
        return fn_id in self._fn_cache or fn_id in self._registered_fns

    def lookup_placement_group(self, pg_id):
        return self._request("lookup_pg", pg_id)

    def pg_ready_ref(self, pg):
        # Blocks until placed, then returns a trivially-ready ref; callers always
        # ray_tpu.get() the result of pg.ready() so the semantics match.
        self._request("pg_ready_ref", pg.id)
        return self.put(True)

    def create_placement_group(self, bundles, strategy, name):
        return self._request("create_pg", bundles, strategy, name)

    def remove_placement_group(self, pg_id):
        self._send(("remove_pg", pg_id))

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="worker-async-get").start()
        return fut

    def runtime_context(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id_hex,
            "worker_id": self.worker_id_hex,
            "task_id": self.current_task_id.hex() if self.current_task_id else None,
            "actor_id": self.actor_id.hex() if self.actor_id else None,
            "accel": self.accel,
        }

    # -- execution -----------------------------------------------------------------
    def _load_fn(self, spec: TaskSpec):
        fn = self._fn_cache.get(spec.fn_id)
        if fn is None:
            if spec.fn_bytes is None:
                fn_bytes = self._request("fetch_fn", spec.fn_id)
            else:
                fn_bytes = spec.fn_bytes
            fn = cloudpickle.loads(fn_bytes)
            self._fn_cache[spec.fn_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec, resolved_locs: List) -> Tuple[list, dict]:
        args, kwargs = cloudpickle.loads(spec.args_meta)
        values = [self._resolve_recovering(o, loc)
                  for o, loc in zip(spec.arg_refs, resolved_locs)]

        def sub(x):
            return values[x.index] if isinstance(x, _RefMarker) else x

        args = [sub(a) for a in args]
        kwargs = {k: sub(v) for k, v in kwargs.items()}
        return args, kwargs

    def execute(self, spec: TaskSpec, resolved_locs: List) -> None:
        # Threaded actors (reference max_concurrency): methods run on a pool so a
        # replica can serve requests concurrently (serve batching/long polls).
        # Named concurrency groups (reference concurrency_group_manager.h) get
        # their own pools so e.g. parked long-poll listeners can never exhaust
        # the default pool and starve control RPCs.
        if spec.kind == "actor_method":
            if (spec.method_name in self._async_methods
                    and spec.num_returns != -1):
                # async actor method: schedule on the per-actor event loop so
                # any number of in-flight calls interleave at awaits
                # (reference actor.py:2352); streaming calls keep the thread
                # path (sync-generator protocol)
                import asyncio

                asyncio.run_coroutine_threadsafe(
                    self._execute_async(spec, resolved_locs), self._ensure_actor_loop())
                return
            group = spec.concurrency_group or self._method_groups.get(
                spec.method_name or "", "")
            if group:
                pool = self._group_pools.get(group)
                if pool is None:
                    # never fall back silently: a typo'd group would land parked
                    # calls on the bounded default pool and reintroduce the
                    # starvation the groups exist to prevent
                    self._send_error(spec, ValueError(
                        f"concurrency group {group!r} was not declared in this "
                        f"actor's concurrency_groups "
                        f"(declared: {sorted(self._group_pools)})"))
                    return
                pool.submit(self._execute_inner, spec, resolved_locs)
                return
            if self._method_pool is not None:
                self._method_pool.submit(self._execute_inner, spec, resolved_locs)
                return
        self._execute_inner(spec, resolved_locs)

    def _execute_inner(self, spec: TaskSpec, resolved_locs: List) -> None:
        self.current_task_id = spec.task_id
        ctx_token = None
        try:
            from ray_tpu.runtime_env import applied as _renv_applied

            import contextlib

            if spec.trace_ctx is not None:
                from ray_tpu.util import tracing

                # a propagated context IS the enable signal for THIS task:
                # is_tracing_enabled honors an active context, so no global
                # flag needs flipping (one traced request must not turn a
                # long-lived worker's tracing on forever)
                ctx_token = tracing.set_trace_context(spec.trace_ctx)
                span_cm = tracing.span(f"task::{spec.name}", {"kind": spec.kind})
            else:
                span_cm = contextlib.nullcontext()
            with span_cm:
                args, kwargs = self._resolve_args(spec, resolved_locs)
                if spec.kind == "task" and spec.runtime_env:
                    with _renv_applied(spec.runtime_env):
                        return self._execute_body(spec, args, kwargs)
                if spec.kind == "actor_creation" and spec.runtime_env:
                    # actors keep their runtime env for their lifetime
                    with _renv_applied(spec.runtime_env, permanent=True):
                        pass
                return self._execute_body(spec, args, kwargs)
        except BaseException as e:  # noqa: BLE001
            self._send_error(spec, e)
        finally:
            if ctx_token is not None:
                # reset the POOLED dispatch/method thread: a leaked context
                # would stitch the next (untraced) request on this thread
                # into this trace and mis-tag its telemetry
                from ray_tpu.util import tracing

                tracing._ctx.reset(ctx_token)
            self.current_task_id = None

    def _send_error(self, spec: TaskSpec, e: BaseException) -> None:
        """Report a task failure (body, arg resolution, or runtime-env application)."""
        tb = traceback.format_exc()
        err = TaskError(e, task_desc=spec.name, tb_str=tb)
        try:
            payload = [
                (oid, object_store.materialize(err, oid, is_error=True))
                for oid in spec.return_ids
            ]
        # graftlint: allow[swallowed-exception] the error object itself failed to pickle: re-report as a plain TaskError with the traceback text
        except Exception:
            # the exception object itself failed to serialize; report a plain failure
            err2 = TaskError(RuntimeError(f"unserializable error: {tb}"), spec.name)
            payload = [
                (oid, object_store.materialize(err2, oid, is_error=True))
                for oid in spec.return_ids
            ]
        self._send(("result", spec.task_id, payload, (spec.name, tb, type(e).__name__)))

    def _execute_body(self, spec: TaskSpec, args, kwargs) -> None:
        try:
            if spec.num_returns == -1 and spec.kind in ("task", "actor_method"):
                # streaming generator task (reference _raylet.pyx:1138): each
                # yielded item becomes its own object under a derived id; the
                # ordinary return carries the final item count
                self._execute_streaming(spec, args, kwargs)
                return
            if spec.kind == "actor_creation":
                cls = self._load_fn(spec)
                self.actor_instance = cls(*args, **kwargs)
                self.actor_id = spec.actor_id
                mc = spec.max_concurrency
                if mc > 1 or spec.concurrency_groups:
                    from concurrent.futures import ThreadPoolExecutor

                    self._method_pool = ThreadPoolExecutor(
                        max_workers=mc, thread_name_prefix="actor-method"
                    )
                for gname, size in (spec.concurrency_groups or {}).items():
                    if size and size > 0:
                        from concurrent.futures import ThreadPoolExecutor

                        self._group_pools[gname] = ThreadPoolExecutor(
                            max_workers=size, thread_name_prefix=f"cg-{gname}")
                    else:
                        self._group_pools[gname] = _ThreadPerCallExecutor(gname)
                self._method_groups = {
                    name: m.get("concurrency_group", "")
                    for name, m in (spec.method_meta or {}).items()
                    if m.get("concurrency_group")
                }
                self._async_methods = {
                    name for name, m in (spec.method_meta or {}).items()
                    if m.get("is_async")
                }
                results = [None]
            elif spec.kind == "actor_method":
                if spec.method_name == "__ray_call__":
                    # Escape hatch (reference ActorHandle.__ray_call__): run an arbitrary
                    # function against the actor instance. Used by dag/ exec loops.
                    fn = args[0]
                    out = fn(self.actor_instance, *args[1:], **kwargs)
                else:
                    method = getattr(self.actor_instance, spec.method_name)
                    out = method(*args, **kwargs)
                results = self._split_returns(out, spec.num_returns)
            else:
                fn = self._load_fn(spec)
                out = fn(*args, **kwargs)
                results = self._split_returns(out, spec.num_returns)
            payload = []
            for oid, value in zip(spec.return_ids, results):
                payload.append((oid, object_store.materialize(value, oid)))
            self._send(("result", spec.task_id, payload, None))
        except BaseException as e:  # noqa: BLE001
            self._send_error(spec, e)
        finally:
            self.current_task_id = None

    def _ensure_actor_loop(self):
        """The actor's asyncio loop, running on its own daemon thread. ONE loop
        per actor: dispatch and method-pool threads may race to create it, and
        asyncio primitives bind to the loop they were created on."""
        with self._loop_lock:
            if self._actor_loop is None:
                import asyncio

                loop = asyncio.new_event_loop()
                threading.Thread(target=loop.run_forever, daemon=True,
                                 name="actor-asyncio").start()
                self._actor_loop = loop
            return self._actor_loop

    async def _execute_async(self, spec: TaskSpec, resolved_locs: List) -> None:
        """Async actor method body: resolve args, await the coroutine, report.
        Runs ON the actor loop; blocking work inside belongs in executors."""
        import contextlib

        _ASYNC_TASK_ID.set(spec.task_id)  # task-scoped (per-asyncio.Task context)
        try:
            if spec.trace_ctx is not None:
                from ray_tpu.util import tracing

                # per-asyncio.Task context: no reset needed, and the active
                # context is itself the enable signal (see _execute_inner)
                tracing.set_trace_context(spec.trace_ctx)
                span_cm = tracing.span(f"task::{spec.name}", {"kind": spec.kind})
            else:
                span_cm = contextlib.nullcontext()
            with span_cm:
                args, kwargs = self._resolve_args(spec, resolved_locs)
                method = getattr(self.actor_instance, spec.method_name)
                out = await method(*args, **kwargs)
                results = self._split_returns(out, spec.num_returns)
                payload = [(oid, object_store.materialize(value, oid))
                           for oid, value in zip(spec.return_ids, results)]
                self._send(("result", spec.task_id, payload, None))
        except BaseException as e:  # noqa: BLE001
            self._send_error(spec, e)

    def _execute_streaming(self, spec: TaskSpec, args, kwargs) -> None:
        from .object_ref import stream_item_id

        # a retried / lineage-reconstructed execution reuses the task id: a
        # stale cancel from the previous attempt must not kill it at item 0
        self._cancelled_streams.discard(spec.task_id)
        if spec.kind == "actor_method":
            if spec.method_name == "__ray_call__":
                out = args[0](self.actor_instance, *args[1:], **kwargs)
            else:
                out = getattr(self.actor_instance, spec.method_name)(*args, **kwargs)
        else:
            out = self._load_fn(spec)(*args, **kwargs)
        count = 0
        import inspect as _inspect

        if _inspect.iscoroutine(out):
            # plain async def under a streaming call: await it, then stream the
            # result as one item (mirrors the sync non-iterator case below)
            import asyncio

            out = iter((asyncio.run_coroutine_threadsafe(
                out, self._ensure_actor_loop()).result(),))
        if hasattr(out, "__anext__"):
            # async generator (async def + yield): drive it on the actor loop,
            # itemizing from this thread
            import asyncio

            loop = self._ensure_actor_loop()

            def drain(agen):
                try:
                    while True:
                        try:
                            yield asyncio.run_coroutine_threadsafe(
                                agen.__anext__(), loop).result()
                        except StopAsyncIteration:
                            return
                finally:
                    # close() on this wrapper (stream cancellation) must reach
                    # the async generator's finally blocks too
                    try:
                        asyncio.run_coroutine_threadsafe(
                            agen.aclose(), loop).result(timeout=10)
                    # graftlint: allow[swallowed-exception] async-generator close during cancellation: the loop may already be gone
                    except Exception:
                        pass

            out = drain(out)
        elif not hasattr(out, "__next__"):
            # non-iterator return under a streaming call: a one-item stream
            # (lists/dicts must not be exploded into their elements)
            out = iter((out,))
        try:
            while spec.task_id not in self._cancelled_streams:
                try:
                    item = next(out)
                except StopIteration:
                    break
                oid = stream_item_id(spec.task_id, count)
                loc = object_store.materialize(item, oid)
                self._send(("stream", spec.task_id, count, oid, loc))
                count += 1
        finally:
            # cancelled (or errored) mid-stream: GeneratorExit into the user
            # generator so its finally blocks run (e.g. engine request abort)
            close = getattr(out, "close", None)
            if close is not None:
                try:
                    close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
            self._cancelled_streams.discard(spec.task_id)
        payload = [(spec.return_ids[0],
                    object_store.materialize(count, spec.return_ids[0]))]
        self._send(("result", spec.task_id, payload, None))

    @staticmethod
    def _split_returns(out, num_returns: int):
        if num_returns == 1:
            return [out]
        out_t = tuple(out)
        if len(out_t) != num_returns:
            raise ValueError(f"expected {num_returns} return values, got {len(out_t)}")
        return list(out_t)

    # -- main loop -------------------------------------------------------------------
    def main_loop(self) -> None:
        self._ensure_recv_thread()
        self._send(("ready", self.worker_id_hex))
        while not self._exit:
            msg = self._task_queue.get()
            kind = msg[0]
            if kind == "task":
                _, spec, resolved_locs = msg
                self.execute(spec, resolved_locs)
            elif kind == "exit":
                break


def worker_main(conn, node_id_hex: str, worker_id_hex: str, accel: str, env: Dict[str, str]):
    """Entry point of a spawned worker process."""
    for k, v in env.items():
        os.environ[k] = v
    log_dir = os.environ.get("RAY_TPU_WORKER_LOG_DIR")
    if log_dir:
        # agent-hosted worker: stdout/stderr go to per-worker files the agent
        # tails back to the head (reference: worker log redirection +
        # log_monitor.py:105 re-printing on the driver). Local workers keep the
        # driver's console (no env set).
        try:
            os.makedirs(log_dir, exist_ok=True)
            for stream, fd in (("out", 1), ("err", 2)):
                f = open(os.path.join(log_dir, f"worker-{worker_id_hex}.{stream}"),
                         "ab", buffering=0)
                os.dup2(f.fileno(), fd)
            sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
            sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
    if accel == "cpu":
        # Never let a CPU worker initialize the TPU runtime. The env var alone is not
        # enough: the sandbox sitecustomize may have pre-imported jax and registered an
        # accelerator PJRT plugin that overrides platform selection at the config level
        # (see tests/conftest.py for the same dance driver-side). The config update must
        # land before any backend query in this process.
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            try:
                import jax

                jax.config.update("jax_platforms", "cpu")
            except Exception as e:  # noqa: BLE001
                import logging

                logging.getLogger("ray_tpu.worker").warning(
                    "failed to force cpu platform on pre-imported jax (%r); "
                    "this cpu worker may grab the TPU", e)
    ctx = WorkerContext(conn, node_id_hex, worker_id_hex, accel)
    global_state.set_worker(ctx)
    try:
        ctx.main_loop()
    except KeyboardInterrupt:
        pass
    finally:
        try:
            conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        sys.exit(0)


def _sample_collapsed_stacks(duration_s: float, hz: float) -> dict:
    """Wall-clock stack sampler: every 1/hz, snapshot sys._current_frames()
    and bump a counter per collapsed stack "thread;func (file:line);..."
    (root-first — flamegraph.pl / speedscope collapsed format). The
    dependency-free analogue of `py-spy record` (reference: dashboard
    reporter module's profiling endpoints)."""
    interval = 1.0 / max(1.0, float(hz))
    deadline = time.monotonic() + float(duration_s)
    me = threading.get_ident()
    counts: dict = {}
    while time.monotonic() < deadline:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue  # the sampler observing itself is pure noise
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}"
                             f":{f.f_lineno})")
                f = f.f_back
            key = names.get(ident, "?") + ";" + ";".join(reversed(parts))
            counts[key] = counts.get(key, 0) + 1
        time.sleep(interval)
    return counts


def _format_thread_stacks() -> str:
    """All thread stacks of this process (reference: py-spy dump via the
    dashboard reporter; this is the dependency-free in-process equivalent)."""
    import traceback

    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(out)


def _main_connect() -> None:
    """Socket-connect worker entry (containerized workers: the in-image process
    cannot inherit the node's mp pipe, so it dials back over an authkey'd
    loopback socket and speaks the identical worker protocol)."""
    import argparse

    from multiprocessing.connection import Client

    p = argparse.ArgumentParser()
    p.add_argument("--connect", required=True)
    p.add_argument("--node-id", required=True)
    p.add_argument("--worker-id", required=True)
    p.add_argument("--accel", default="cpu")
    args = p.parse_args()
    host, _, port = args.connect.rpartition(":")
    key = bytes.fromhex(os.environ["RAY_TPU_WORKER_AUTHKEY"])
    conn = Client((host or "127.0.0.1", int(port)), authkey=key)
    worker_main(conn, args.node_id, args.worker_id, args.accel, {})


if __name__ == "__main__":
    _main_connect()
