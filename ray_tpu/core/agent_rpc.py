"""Typed gRPC control plane for the agent<->head channel.

Capability parity: reference src/ray/rpc/ (GrpcServer/GrpcClient,
ClientCallManager) + src/ray/protobuf/node_manager.proto — raylet<->GCS
control traffic rides typed protobuf over gRPC, not pickled Python. One
long-lived bidirectional stream per agent carries every control message
(protos/node_agent.proto); worker PIPE payloads remain opaque bytes relayed
verbatim (they originate and terminate inside the head's own trust domain).

The head never unpickles anything received from a semi-trusted agent. Auth:
the per-cluster session key rides the stream's initial metadata and is
compared constant-time. gRPC supplies keepalive, flow control, and per-stream
multiplexing; app-level request deadlines stay in AgentHandle.call.

Codec design: node.py / node_agent.py keep their tuple-shaped message logic —
this module converts tuples <-> protobuf at the transport boundary, so the
message semantics live in one place and the wire format in another.
"""
from __future__ import annotations

import hmac
import queue
import threading
from typing import Iterator, Optional, Tuple

from ray_tpu.protos import node_agent_pb2 as pb

_SERVICE = "ray_tpu.rpc.NodeAgentService"
_METHOD = f"/{_SERVICE}/AgentChannel"
_AUTH_KEY = "rt-auth-bin"

_ERR_KINDS = {
    "os": OSError,
    "timeout": TimeoutError,
    "key": KeyError,
}


def _err_kind(e: BaseException) -> str:
    from . import object_store

    if isinstance(e, object_store.ObjectLost):
        return "object_lost"
    if isinstance(e, TimeoutError):
        return "timeout"
    if isinstance(e, (OSError, EOFError)):
        return "os"
    if isinstance(e, KeyError):
        return "key"
    return "other"


def make_error(kind: str, msg: str) -> Exception:
    if kind == "object_lost":
        from . import object_store

        return object_store.ObjectLost(msg)
    return _ERR_KINDS.get(kind, RuntimeError)(msg)


# ---- Scalar / Location codec ---------------------------------------------------

def _scalar(v) -> pb.Scalar:
    if isinstance(v, bool):
        return pb.Scalar(flag=v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return pb.Scalar(b=bytes(v))
    if isinstance(v, str):
        return pb.Scalar(s=v)
    if isinstance(v, int):
        return pb.Scalar(i=v)
    if isinstance(v, float):
        return pb.Scalar(d=v)
    raise TypeError(f"non-scalar location element {type(v)!r}")


def _unscalar(s: pb.Scalar):
    return getattr(s, s.WhichOneof("v"))


def encode_loc(loc) -> pb.Location:
    return pb.Location(parts=[_scalar(v) for v in loc])


def decode_loc(m: pb.Location) -> Optional[tuple]:
    if not m.parts:
        return None
    return tuple(_unscalar(s) for s in m.parts)


# ---- head -> agent -------------------------------------------------------------

def encode_head_msg(msg: tuple) -> pb.HeadMessage:
    kind = msg[0]
    if kind == "spawn_worker":
        sw = pb.SpawnWorker(worker_id=msg[1], accel=msg[2])
        if len(msg) > 3 and msg[3]:
            sw.extra_env.update(msg[3])
        if len(msg) > 4 and msg[4]:
            sw.has_container = True
            sw.container_image = msg[4]["image"]
            sw.container_run_options.extend(msg[4].get("run_options") or ())
        return pb.HeadMessage(spawn_worker=sw)
    if kind == "to_worker":
        return pb.HeadMessage(to_worker=pb.ToWorker(worker_id=msg[1],
                                                    payload=msg[2]))
    if kind == "kill_worker":
        return pb.HeadMessage(kill_worker=pb.KillWorker(worker_id=msg[1]))
    if kind == "free_object":
        return pb.HeadMessage(free_object=pb.FreeObject(loc=encode_loc(msg[1])))
    if kind == "shutdown":
        return pb.HeadMessage(shutdown=pb.Shutdown())
    if kind == "control_backpressure":
        return pb.HeadMessage(control_backpressure=pb.ControlBackpressure(
            level=msg[1], min_interval_s=msg[2]))
    if kind == "req":
        _, req_id, op, args = msg
        r = pb.AgentRequest(req_id=req_id, op=op)
        if op == "fetch_object":
            r.loc.CopyFrom(encode_loc(args[0]))
        elif op == "store_object":
            oid, data, is_error = args
            r.oid, r.data, r.is_error = oid.binary(), data, is_error
        elif op == "pull_object":
            oid, loc, addr = args
            r.oid = oid.binary()
            r.loc.CopyFrom(encode_loc(loc))
            r.host, r.port = (addr[0] or ""), int(addr[1])
        elif op == "gc_dead_owners":
            r.keep.extend(args[0])
        else:
            raise ValueError(f"unknown agent op {op!r}")
        return pb.HeadMessage(request=r)
    raise ValueError(f"unknown head message kind {kind!r}")


def decode_head_msg(m: pb.HeadMessage) -> tuple:
    kind = m.WhichOneof("msg")
    if kind == "spawn_worker":
        sw = m.spawn_worker
        container = ({"image": sw.container_image,
                      "run_options": list(sw.container_run_options)}
                     if sw.has_container else None)
        return ("spawn_worker", sw.worker_id, sw.accel,
                dict(sw.extra_env) or None, container)
    if kind == "to_worker":
        return ("to_worker", m.to_worker.worker_id, m.to_worker.payload)
    if kind == "kill_worker":
        return ("kill_worker", m.kill_worker.worker_id)
    if kind == "free_object":
        return ("free_object", decode_loc(m.free_object.loc))
    if kind == "shutdown":
        return ("shutdown",)
    if kind == "control_backpressure":
        return ("control_backpressure", m.control_backpressure.level,
                m.control_backpressure.min_interval_s)
    if kind == "request":
        r = m.request
        if r.op == "fetch_object":
            args: tuple = (decode_loc(r.loc),)
        elif r.op == "store_object":
            from .ids import ObjectID

            args = (ObjectID(r.oid), r.data, r.is_error)
        elif r.op == "pull_object":
            from .ids import ObjectID

            args = (ObjectID(r.oid), decode_loc(r.loc),
                    (r.host or None, r.port))
        elif r.op == "gc_dead_owners":
            args = (set(r.keep),)
        else:
            args = ()
        return ("req", r.req_id, r.op, args)
    if kind == "welcome":
        return ("welcome", {"node_id": m.welcome.node_id,
                            "worker_env": dict(m.welcome.worker_env),
                            "object_store_memory": m.welcome.object_store_memory})
    if kind == "welcome_back":
        return ("welcome_back", {"keep_workers": list(m.welcome_back.keep_workers)})
    raise ValueError(f"unknown head proto {kind!r}")


# ---- agent -> head -------------------------------------------------------------

def encode_agent_msg(msg: tuple) -> pb.AgentMessage:
    kind = msg[0]
    if kind == "heartbeat":
        return pb.AgentMessage(heartbeat=pb.Heartbeat(time=msg[1]))
    if kind == "from_worker":
        return pb.AgentMessage(from_worker=pb.FromWorker(worker_id=msg[1],
                                                         payload=msg[2]))
    if kind == "worker_death":
        return pb.AgentMessage(worker_death=pb.WorkerDeath(worker_id=msg[1]))
    if kind == "worker_log":
        return pb.AgentMessage(worker_log=pb.WorkerLog(worker_id=msg[1],
                                                       stream=msg[2], text=msg[3]))
    if kind == "node_metrics":
        _, seq, agent_time, worker_count, metrics_json, telemetry_json, \
            flush_interval_s = msg
        return pb.AgentMessage(node_metrics=pb.NodeMetrics(
            seq=seq, agent_time=agent_time, worker_count=worker_count,
            metrics_json=metrics_json, telemetry_json=telemetry_json,
            flush_interval_s=flush_interval_s))
    if kind == "register":
        _, resources, labels, max_workers, extras = msg
        return pb.AgentMessage(register=pb.Register(
            resources=resources, labels=labels or {}, max_workers=max_workers,
            data_port=int((extras or {}).get("data_port") or 0)))
    if kind == "reregister":
        _, node_hex, resources, labels, max_workers, extras = msg
        rr = pb.Reregister(
            node_id=node_hex,
            info=pb.Register(resources=resources, labels=labels or {},
                             max_workers=max_workers,
                             data_port=int((extras or {}).get("data_port") or 0)),
            arena=(extras or {}).get("arena") or "",
        )
        for wid, accel in (extras or {}).get("workers", ()):
            rr.workers.add(worker_id=wid, accel=accel)
        for oid, size, flags in (extras or {}).get("objects", ()):
            rr.objects.add(oid=oid, size=size, flags=flags)
        return pb.AgentMessage(reregister=rr)
    if kind == "reply":
        _, req_id, ok, value = msg
        r = pb.AgentReply(req_id=req_id)
        if not ok:
            r.error_kind = _err_kind(value)
            r.error = str(value) or repr(value)
        elif isinstance(value, tuple) and len(value) == 2 and isinstance(value[0], (bytes, memoryview, bytearray)):
            r.data, r.is_error = bytes(value[0]), bool(value[1])  # fetch_object
        elif isinstance(value, tuple):
            r.loc.CopyFrom(encode_loc(value))  # store/pull -> local location
        else:
            r.ok = bool(value)  # gc_dead_owners
        return pb.AgentMessage(reply=r)
    raise ValueError(f"unknown agent message kind {kind!r}")


def decode_agent_msg(m: pb.AgentMessage) -> tuple:
    kind = m.WhichOneof("msg")
    if kind == "heartbeat":
        return ("heartbeat", m.heartbeat.time)
    if kind == "from_worker":
        return ("from_worker", m.from_worker.worker_id, m.from_worker.payload)
    if kind == "worker_death":
        return ("worker_death", m.worker_death.worker_id)
    if kind == "worker_log":
        return ("worker_log", m.worker_log.worker_id, m.worker_log.stream,
                m.worker_log.text)
    if kind == "node_metrics":
        nm = m.node_metrics
        return ("node_metrics", nm.seq, nm.agent_time, nm.worker_count,
                nm.metrics_json, nm.telemetry_json, nm.flush_interval_s)
    if kind == "register":
        r = m.register
        return ("register", dict(r.resources), dict(r.labels), r.max_workers,
                {"data_port": r.data_port or None})
    if kind == "reregister":
        rr = m.reregister
        return ("reregister", rr.node_id, dict(rr.info.resources),
                dict(rr.info.labels), rr.info.max_workers,
                {"data_port": rr.info.data_port or None,
                 "arena": rr.arena or None,
                 "workers": [(w.worker_id, w.accel) for w in rr.workers],
                 "objects": [(o.oid, o.size, o.flags) for o in rr.objects]})
    if kind == "reply":
        r = m.reply
        if r.error_kind:
            return ("reply", r.req_id, False, make_error(r.error_kind, r.error))
        loc = decode_loc(r.loc)
        if loc is not None:
            return ("reply", r.req_id, True, loc)
        if r.data or r.is_error or not r.ok:
            # fetch_object result (data may legitimately be empty bytes)
            return ("reply", r.req_id, True, (r.data, r.is_error))
        return ("reply", r.req_id, True, r.ok)
    raise ValueError(f"unknown agent proto {kind!r}")


# ---- transport: head-side gRPC server ------------------------------------------

# Max frames coalesced into one gRPC message (CONFIG.agent_batch_max; read at
# use so env changes apply live). Batching only packs what is ALREADY queued
# when the writer wakes (never waits), so it adds zero latency while
# amortizing grpc-python's ~0.15-0.2 ms per-message cost under load.
def _batch_max() -> int:
    from ray_tpu.config import CONFIG

    return CONFIG.agent_batch_max


def _queue_depth() -> int:
    from ray_tpu.config import CONFIG

    return CONFIG.agent_queue_depth


def _send_timeout_s() -> float:
    from ray_tpu.config import CONFIG

    return CONFIG.agent_send_timeout_s


def _drain_batch(q: "queue.Queue", first):
    """Greedily collect already-queued frames after `first`. Returns the single
    message as-is, or a list (>=2) for the caller to wrap in a batch. A None
    shutdown sentinel found mid-drain is re-queued so the caller's next get
    still sees it after the collected frames are flushed."""
    items = [first]
    cap = _batch_max()
    while len(items) < cap:
        try:
            nxt = q.get_nowait()
        except queue.Empty:
            break
        if nxt is None:
            # Re-queued at the BACK: ordering still holds because a None can
            # only follow close(), and both senders (AgentStream.send,
            # AgentChannel.send) refuse new frames once their closed flag is
            # set — so no frame can be enqueued after the sentinel for this
            # put to jump ahead of. If that send()-after-close guard ever
            # moves, switch this queue to a deque + appendleft (ADVICE r4).
            q.put(None)
            break
        items.append(nxt)
    if len(items) == 1:
        return items[0]
    return items


class AgentStream:
    """Head-side view of one connected agent stream (Connection-ish: the
    Cluster hands tuples to send(); incoming tuples flow to its callback)."""

    # bounded outbound buffers: a stalled/dead peer must exert BACKPRESSURE
    # (send raises after the grace) instead of accumulating frames in RAM
    # CONFIG-backed via the module helpers below (read at use; env changes
    # apply live). Plain functions, NOT properties: HeadConnection reads these
    # at CLASS level, where a property object would silently replace the number.
    QUEUE_DEPTH = None  # use _queue_depth()
    SEND_TIMEOUT_S = None  # use _send_timeout_s()

    def __init__(self, peer_ip: Optional[str]):
        self.peer_ip = peer_ip
        self._out: "queue.Queue[Optional[pb.HeadMessage]]" = queue.Queue(
            maxsize=_queue_depth())
        self.closed = threading.Event()
        # set by the Cluster during on_connect, before the reader starts
        self.on_message = None
        self.on_disconnect = None

    def send(self, msg: tuple) -> None:
        if self.closed.is_set():
            raise OSError("agent stream closed")
        try:
            self._out.put(encode_head_msg(msg), timeout=_send_timeout_s())
        except queue.Full:
            raise OSError("agent stream backed up (peer stalled)")

    def send_welcome(self, payload: dict) -> None:
        self._out.put(pb.HeadMessage(welcome=pb.Welcome(
            node_id=payload["node_id"], worker_env=payload["worker_env"],
            object_store_memory=int(payload.get("object_store_memory") or 0))))

    def send_welcome_back(self, payload: dict) -> None:
        self._out.put(pb.HeadMessage(welcome_back=pb.WelcomeBack(
            keep_workers=payload.get("keep_workers") or [])))

    def close(self) -> None:
        self.closed.set()  # _outbound notices within its poll slice

    def _outbound(self) -> Iterator[pb.HeadMessage]:
        while True:
            try:
                m = self._out.get(timeout=0.5)
            except queue.Empty:
                if self.closed.is_set():
                    return
                continue
            if m is None:
                return
            batched = _drain_batch(self._out, m)
            yield (batched if isinstance(batched, pb.HeadMessage)
                   else pb.HeadMessage(batch=pb.HeadBatch(items=batched)))


class AgentRpcServer:
    """gRPC server accepting node-agent streams (reference GrpcServer)."""

    def __init__(self, host: str, port: int, authkey: bytes, on_connect):
        """on_connect(stream, first_msg_tuple) -> bool: the Cluster's
        registration hook; False rejects the stream."""
        import grpc

        self._authkey = authkey
        self._on_connect = on_connect
        handler = grpc.method_handlers_generic_handler(_SERVICE, {
            "AgentChannel": grpc.stream_stream_rpc_method_handler(
                self._channel,
                request_deserializer=pb.AgentMessage.FromString,
                response_serializer=pb.HeadMessage.SerializeToString,
            )})
        from concurrent.futures import ThreadPoolExecutor

        # 2 threads per agent stream (handler + request reader): cap ~64 agents
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=128, thread_name_prefix="rt-grpc"),
            options=[("grpc.keepalive_time_ms", 10000),
                     ("grpc.keepalive_timeout_ms", 10000),
                     ("grpc.max_receive_message_length", 512 * 1024 * 1024),
                     ("grpc.max_send_message_length", 512 * 1024 * 1024)])
        self._server.add_generic_rpc_handlers((handler,))
        from ray_tpu.core import tls_utils

        if tls_utils.use_tls():
            # mTLS (reference src/ray/rpc/ TLS-capable GrpcServer): plaintext
            # dials are refused at the handshake
            self.port = self._server.add_secure_port(
                f"{host}:{port}", tls_utils.grpc_server_credentials())
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def _authed(self, context) -> bool:
        for k, v in context.invocation_metadata():
            if k == _AUTH_KEY:
                return hmac.compare_digest(v, self._authkey)
        return False

    def _channel(self, request_iterator, context):
        import grpc

        if not self._authed(context):
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad cluster authkey")
        peer = context.peer()  # "ipv4:1.2.3.4:56789"
        peer_ip = None
        if peer.startswith(("ipv4:", "ipv6:")):
            peer_ip = peer.split(":", 1)[1].rsplit(":", 1)[0].strip("[]")
        stream = AgentStream(peer_ip)
        try:
            first_pb = next(request_iterator)
        except StopIteration:
            return
        trailing = ()
        if first_pb.WhichOneof("msg") == "batch":
            # register raced other frames into one coalesced message: the first
            # item is the registration, the rest flow through on_message below
            items = list(first_pb.batch.items)
            first_pb, trailing = items[0], items[1:]
        first = decode_agent_msg(first_pb)
        if not self._on_connect(stream, first):
            return
        for t in trailing:
            try:
                if stream.on_message is not None:
                    stream.on_message(decode_agent_msg(t))
            except Exception:
                import traceback

                traceback.print_exc()

        def reader():
            try:
                for m in request_iterator:
                    items = (m.batch.items if m.WhichOneof("msg") == "batch"
                             else (m,))
                    for item in items:
                        try:
                            if stream.on_message is not None:
                                stream.on_message(decode_agent_msg(item))
                        except Exception:
                            # one bad/undecodable message must not silently
                            # kill the whole node — keep stream, surface error
                            import traceback

                            traceback.print_exc()
            # graftlint: allow[swallowed-exception] malformed frame from a peer is dropped; persistent breakage trips the stream reaper
            except Exception:
                pass  # transport ended: fall through to the death path
            finally:
                stream.close()
                if stream.on_disconnect is not None:
                    stream.on_disconnect()

        threading.Thread(target=reader, daemon=True,
                         name="rt-grpc-agent-read").start()
        yield from stream._outbound()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


# ---- transport: agent-side gRPC client -----------------------------------------

class HeadConnection:
    """Agent-side stream to the head: send(tuple) out, recv() tuples in."""

    def __init__(self, host: str, port: int, authkey: bytes,
                 connect_timeout: float = 10.0):
        import grpc

        from ray_tpu.core import tls_utils

        opts = [("grpc.keepalive_time_ms", 10000),
                ("grpc.max_receive_message_length", 512 * 1024 * 1024),
                ("grpc.max_send_message_length", 512 * 1024 * 1024)]
        if tls_utils.use_tls():
            self._channel = grpc.secure_channel(
                f"{host}:{port}", tls_utils.grpc_channel_credentials(),
                options=opts + [("grpc.ssl_target_name_override",
                                 tls_utils.TLS_TARGET_NAME)])
        else:
            self._channel = grpc.insecure_channel(f"{host}:{port}", options=opts)
        grpc.channel_ready_future(self._channel).result(timeout=connect_timeout)
        # bounded for backpressure: a dead/stalled head makes send() RAISE
        # after the grace instead of buffering frames into a void
        self._out: "queue.Queue[Optional[pb.AgentMessage]]" = queue.Queue(
            maxsize=_queue_depth())
        self._closed = threading.Event()
        call = self._channel.stream_stream(
            _METHOD, request_serializer=pb.AgentMessage.SerializeToString,
            response_deserializer=pb.HeadMessage.FromString)
        self._resp = call(self._requests(), metadata=((_AUTH_KEY, authkey),))

    def _requests(self):
        while True:
            try:
                m = self._out.get(timeout=0.5)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if m is None:
                return
            batched = _drain_batch(self._out, m)
            yield (batched if isinstance(batched, pb.AgentMessage)
                   else pb.AgentMessage(batch=pb.AgentBatch(items=batched)))

    def send(self, msg: tuple) -> None:
        if self._closed.is_set():
            raise OSError("head stream closed")
        try:
            self._out.put(encode_agent_msg(msg),
                          timeout=_send_timeout_s())
        except queue.Full:
            raise OSError("head stream backed up (head stalled)")

    def recv(self) -> tuple:
        """Next head message; raises EOFError ONLY when the transport ends —
        a single undecodable message (version skew) is skipped with a
        traceback rather than tearing down a healthy stream."""
        while True:
            pending = getattr(self, "_pending_in", None)
            if pending:
                return pending.popleft()
            try:
                m = next(self._resp)
            except StopIteration:
                raise EOFError("head stream closed")
            except Exception as e:
                raise EOFError(f"head stream failed: {e}") from e
            if m.WhichOneof("msg") == "batch":
                import collections

                if pending is None:
                    pending = self._pending_in = collections.deque()
                for item in m.batch.items:
                    # per-item skip: one undecodable frame must not discard
                    # the rest of the batch (same contract as single frames)
                    try:
                        pending.append(decode_head_msg(item))
                    except Exception:
                        import traceback

                        traceback.print_exc()
                continue
            try:
                return decode_head_msg(m)
            except Exception:
                import traceback

                traceback.print_exc()

    def close(self) -> None:
        self._closed.set()
        try:
            self._channel.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
