"""Three-tier object store: inline bytes, C++ shared-memory arena, per-object segments.

Capability parity: reference plasma store (src/ray/object_manager/plasma/store.h:55) +
CoreWorker memory store (src/ray/core_worker/store_provider/). Differences by design:
- Large objects live in one node-wide C++ arena (_native/shm_store.cc): create/seal are
  library calls into shared memory, not a socket round-trip to a plasma daemon; the
  allocator is a boundary-tag heap (plasma uses dlmalloc behind a store process).
- When the arena is full or absent, producers fall back to creating a per-object POSIX
  shm segment themselves (this doubles as "spilling" pressure relief).
- Readers map zero-copy; numpy arrays deserialized from the arena or a segment are views
  over the mapping (pickle5 out-of-band buffers, see serialization.py).
"""
from __future__ import annotations

import os
import threading
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .ids import ObjectID

from ray_tpu.config import memoized_flag

# Objects below this many serialized bytes travel inline through control
# pipes. Per-put fast path (~80k+ puts/s): memoized against the raw env string.
_inline_threshold = memoized_flag("inline_threshold_bytes")

# Location tuples:
#   ("inline", frame_bytes, is_error)
#   ("arena", arena_name, oid_bytes, nbytes, is_error)
#   ("shm", name, nbytes, is_error)
#   ("disk", path, nbytes, is_error)    <- spilled (reference local_object_manager.h:43)
#   ("remote", host_key, inner_loc)     <- lives on another host's node agent; only
#       the head's directory holds these (multi-host plane, reference
#       object_manager.h:119 cross-node transfer); workers always receive a
#       host-local location after the head localizes it
Location = Tuple

# ------------------------------------------------------------------- arena plumbing
_ARENA_ENV = "RAY_TPU_ARENA"
_arena_lock = threading.Lock()
_arenas: Dict[str, Any] = {}
_arena_default: Optional[Any] = None
_arena_disabled = False


def init_arena(capacity: int) -> Optional[str]:
    """Create this node's arena (coordinator side). Returns its name or None."""
    global _arena_default, _arena_disabled
    name = f"/rtpu_arena_{os.getpid()}_{os.urandom(3).hex()}"
    try:
        from ray_tpu._native.shm_store import Arena

        a = Arena.create(name, capacity)
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (_arena_disabled = True) by design
    except Exception:
        _arena_disabled = True
        return None
    with _arena_lock:
        _arenas[name] = a
        _arena_default = a
    os.environ[_ARENA_ENV] = name  # driver-side materialize in this process
    return name


def destroy_arena() -> None:
    global _arena_default
    with _arena_lock:
        a = _arena_default
        _arena_default = None
    if a is not None and a.owner:
        try:
            a.unlink()
            a.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        os.environ.pop(_ARENA_ENV, None)


def _open_arena(name: str):
    with _arena_lock:
        a = _arenas.get(name)
    if a is None:
        from ray_tpu._native.shm_store import Arena

        a = Arena.open(name)
        with _arena_lock:
            _arenas[name] = a
    return a


def _default_arena():
    """Writer-side arena: created locally (coordinator) or attached via env (workers)."""
    global _arena_default, _arena_disabled
    if _arena_default is None and not _arena_disabled:
        name = os.environ.get(_ARENA_ENV)
        if not name:
            return None
        try:
            _arena_default = _open_arena(name)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (_arena_disabled = True) by design
        except Exception:
            _arena_disabled = True
    return _arena_default


from .exceptions import ObjectLostError


class ObjectLost(ObjectLostError):
    pass


def materialize(obj: Any, oid: ObjectID, is_error: bool = False) -> Location:
    """Serialize obj and place it: small -> inline, large -> arena, overflow -> segment."""
    from ray_tpu.experimental import device_objects

    if not is_error and device_objects.is_device_array(obj):
        # same-process resolves return the original device array (no host copy);
        # cross-process consumers pull device-to-device via the transfer plane
        # when enabled (wrap_for_store), else use the serialized host copy
        device_objects.stash(oid.binary(), obj)
        obj = device_objects.wrap_for_store(oid.binary(), obj)
    ser = serialization.serialize(obj)
    size = ser.frame_bytes
    if size < _inline_threshold():
        return ("inline", ser.to_bytes(), is_error)
    arena = _default_arena()
    if arena is not None:
        buf = arena.create_object(oid.binary(), size)
        if buf is not None:
            try:
                ser.write_into(buf)
            finally:
                buf.release()
            arena.seal(oid.binary())
            if is_error:
                # recorded in the arena entry too, so a rebuilt directory
                # (head restart; agent re-reports contents) keeps raising it
                arena.set_flags(oid.binary(), 1)
            return ("arena", arena.name, oid.binary(), size, is_error)
    name = "rt_" + oid.hex()[:24]
    seg = shared_memory.SharedMemory(name=name, create=True, size=size)
    try:
        ser.write_into(seg.buf)
    finally:
        seg.close()
    return ("shm", name, size, is_error)


from ray_tpu.core.data_plane import PinnedRead


def read_pinned(loc: Location, offset: int = 0,
                length: Optional[int] = None) -> PinnedRead:
    """Zero-copy read: a PinnedRead whose view maps the object's frame bytes
    (or the clamped [offset, offset+length) range of them) STRAIGHT from the
    backing storage — no bytes materialized.

    The view is pinned against concurrent spill_lru/free_local for its
    lifetime: arena reads hold a C++ reader pin (delete defers the free to the
    last unpin, shm_store.cc kCondemned), shm/disk reads hold the mapping
    itself (unlink leaves live mappings valid; close defers while views are
    exported). Callers MUST release() — the data plane does so when the
    transfer ends, so a pull in flight can never observe torn bytes."""
    if offset < 0 or (length is not None and length < 0):
        raise ValueError(f"negative slice ({offset}, {length})")
    kind = loc[0]

    def clamp(size: int) -> Tuple[int, int]:
        end = size if length is None else min(offset + length, size)
        return min(offset, size), end

    if kind == "inline":
        _, frame, is_error = loc
        start, end = clamp(len(frame))
        return PinnedRead(memoryview(frame)[start:end], is_error)
    if kind == "arena":
        _, name, oid_bytes, size, is_error = loc
        arena = _open_arena(name)
        view = arena.get(oid_bytes)  # reader pin held until release()
        if view is None:
            raise ObjectLost(f"arena object {oid_bytes.hex()} was freed or lost")
        start, end = clamp(size)

        def unpin(v=view, a=arena, o=bytes(oid_bytes)):
            try:
                v.release()
            except BufferError:
                pass
            a.unpin(o)

        return PinnedRead(view[start:end], is_error, release=unpin)
    if kind == "shm":
        _, name, size, is_error = loc
        try:
            seg = _segment_cache.open(name)
        except FileNotFoundError:
            raise ObjectLost(f"shm segment {name} was freed or lost") from None
        start, end = clamp(size)
        # the exported view IS the pin: a concurrent drop()/unlink leaves this
        # mapping valid (close raises BufferError and the handle is parked)
        return PinnedRead(memoryview(seg.buf)[start:end], is_error)
    if kind == "disk":
        _, path, size, is_error = loc
        import mmap as _mmap

        try:
            f = open(path, "rb")
        except OSError:
            raise ObjectLost(f"spilled object file {path} was lost") from None
        try:
            try:
                m = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            except (ValueError, OSError):
                raise ObjectLost(
                    f"spilled object file {path} was lost") from None
        finally:
            f.close()
        start, end = clamp(size)

        def close_map(mm=m):
            try:
                mm.close()
            except BufferError:
                pass

        return PinnedRead(memoryview(m)[start:end], is_error, release=close_map)
    raise ValueError(f"unknown location kind {kind!r}")


def read_pinned_any(loc: Location) -> PinnedRead:
    """Zero-copy data-plane read dispatcher (the read_fn node/agent DataServers
    serve with): a plain location pins the whole frame, a
    ``("slice", inner_loc, offset, length)`` wrapper pins only that byte range
    — striped pulls and ring steps fetch range k of a large object without the
    serving node copying anything out of shared memory."""
    if loc and loc[0] == "slice":
        _, inner, offset, length = loc
        return read_pinned(inner, int(offset), int(length))
    return read_pinned(loc)


def read_raw(loc: Location) -> Tuple[bytes, bool]:
    """Read an object's serialized frame bytes at a local location.

    Materializing fallback for paths that need an owned bytes object (head
    relay, agent fetch_object); the data plane itself streams read_pinned_any
    views without this copy. Returns (frame_bytes, is_error)."""
    if loc[0] == "inline":
        return loc[1], loc[2]
    with read_pinned(loc) as pr:
        return bytes(pr.view), pr.is_error


def read_raw_slice(loc: Location, offset: int, length: int) -> Tuple[bytes, bool]:
    """Read `length` bytes at `offset` of an object's serialized frame without
    materializing (or copying) the rest of the object. Out-of-range requests
    are clamped to the frame (a zero-length tail read returns b"")."""
    with read_pinned(loc, offset, length) as pr:
        return bytes(pr.view), pr.is_error


def read_raw_any(loc: Location) -> Tuple[bytes, bool]:
    """Materializing twin of read_pinned_any (legacy data-plane read fn)."""
    with read_pinned_any(loc) as pr:
        return bytes(pr.view), pr.is_error


def loc_meta(loc: Location) -> Tuple[Optional[int], bool]:
    """(frame_size, is_error) as recorded in a location tuple, without touching
    the bytes — (None, False) when the location doesn't carry a size. Pullers
    use the size to plan stripes BEFORE dialing and to pre-create the
    destination mapping."""
    kind = loc[0] if loc else None
    if kind == "inline":
        return len(loc[1]), loc[2]
    if kind == "arena":
        return loc[3], loc[4]
    if kind in ("shm", "disk"):
        return loc[2], loc[3]
    if kind == "slice":
        _, inner, offset, length = loc
        size, is_error = loc_meta(inner)
        if size is None:
            return None, is_error
        start = min(int(offset), size)
        return max(0, min(start + int(length), size) - start), is_error
    return None, False


def write_raw(data: bytes, oid: ObjectID, is_error: bool = False) -> Location:
    """Place already-serialized frame bytes locally (receiving side of a
    cross-host transfer): create_raw's allocation policy (arena first,
    per-object segment fallback), filled from an owned buffer and sealed."""
    tgt = create_raw(oid, len(data))
    try:
        tgt.view[:len(data)] = data
    except BaseException:
        tgt.abort()
        raise
    return tgt.seal(is_error)


class RawTarget:
    """A pre-created local destination for an incoming object's frame bytes.

    The receiving side of a zero-copy transfer: create_raw() allocates the
    final backing (arena slot / shm segment / small-object buffer) BEFORE any
    byte arrives, the data plane recv's chunk frames straight into `view`, and
    seal() publishes the location — the pulled object is never staged in an
    intermediate bytes object. abort() tears the allocation down if the
    transfer fails (arena delete defers to any late reader unpin)."""

    def __init__(self, kind: str, size: int, view: memoryview, *,
                 arena=None, oid_bytes: bytes = b"", seg=None, name: str = ""):
        self.kind = kind
        self.size = size
        self.view = view
        self._arena = arena
        self._oid_bytes = oid_bytes
        self._seg = seg
        self._name = name
        self._done = False

    def _release_view(self) -> None:
        try:
            self.view.release()
        except BufferError:
            pass

    def seal(self, is_error: bool = False) -> Location:
        if self._done:
            raise RuntimeError("RawTarget already sealed or aborted")
        self._done = True
        if self.kind == "inline":
            frame = bytes(self.view)
            self._release_view()
            return ("inline", frame, is_error)
        if self.kind == "arena":
            self._release_view()
            self._arena.seal(self._oid_bytes)
            if is_error:
                self._arena.set_flags(self._oid_bytes, 1)
            return ("arena", self._arena.name, self._oid_bytes, self.size,
                    is_error)
        self._release_view()
        try:
            self._seg.close()
        except BufferError:
            _unclosable_segments.append(self._seg)
        return ("shm", self._name, self.size, is_error)

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        self._release_view()
        if self.kind == "arena":
            try:
                self._arena.delete(self._oid_bytes)
            # graftlint: allow[swallowed-exception] arena slot already deleted by a racing free/spill; refcount owns correctness
            except Exception:
                pass
        elif self.kind == "shm":
            try:
                self._seg.close()
            except BufferError:
                _unclosable_segments.append(self._seg)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            try:
                shared_memory.SharedMemory(name=self._name).unlink()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass


def create_raw(oid: ObjectID, size: int) -> RawTarget:
    """Allocate the local backing an incoming frame of `size` bytes will land
    in (arena first, per-object segment fallback, plain buffer below the
    inline threshold) — the write side of write_raw, split out so transfers
    can fill it in place instead of handing over a finished bytes object."""
    if size < _inline_threshold():
        return RawTarget("inline", size, memoryview(bytearray(size)))
    arena = _default_arena()
    if arena is not None:
        buf = arena.create_object(oid.binary(), size)
        if buf is not None:
            return RawTarget("arena", size, buf, arena=arena,
                             oid_bytes=oid.binary())
    # randomized suffix: the source side's materialize() segment for this oid
    # may share this machine's /dev/shm namespace (same-host "multi-host" test
    # topology), so the deterministic name would collide
    name = "rt_" + oid.hex()[:16] + os.urandom(4).hex()
    seg = shared_memory.SharedMemory(name=name, create=True, size=size)
    return RawTarget("shm", size, memoryview(seg.buf)[:size], seg=seg, name=name)


def try_map_local(loc: Location) -> bool:
    """Probe whether `loc`'s backing storage is directly readable from THIS
    process — true exactly when the "remote" source shares this machine's
    shm/disk namespace (colocated node processes: head + agent on one host,
    the single-host pod test topology). The successful probe leaves the
    segment/arena handle cached, so later reads keep working even if the
    source node later unlinks the name. Names are oid-derived + random, so a
    cross-host name collision is not a practical concern."""
    try:
        pr = read_pinned(loc, 0, 0)
    except (ObjectLost, OSError, ValueError, KeyError):
        return False
    pr.release()
    return True


def pull_to_store(client, addr, loc: Location, oid: ObjectID) -> Location:
    """Destination side of a direct node-to-node transfer, zero-copy end to
    end: plan stripes from the location's recorded size, pre-create the local
    backing, land every chunk frame straight in it (DataClient recv-into), and
    seal in place. Replaces the pull-bytes-then-write_raw two-copy dance on the
    head and node-agent transfer routes.

    Fully zero-byte fast path: when the source location is readable in place
    (same-host topology, see try_map_local) the destination adopts it outright
    — the mapping is shared, nothing moves, matching the local get path's
    zero-copy semantics. Frees stay correct because both sides' free of the
    same segment/arena entry is idempotent and only fires at global refcount
    zero."""
    from ray_tpu.config import CONFIG
    from ray_tpu.util import telemetry

    if CONFIG.transfer_same_host_map and try_map_local(loc):
        size, _ = loc_meta(loc)
        telemetry.get_counter(
            "transfer_bytes_total", "object bytes pulled over the data plane",
            tag_keys=("path",)).inc(float(size or 0), tags={"path": "mapped"})
        telemetry.get_counter(
            "transfer_pulls_total", "completed data-plane pulls",
            tag_keys=("path",)).inc(1.0, tags={"path": "mapped"})
        if telemetry.enabled():
            telemetry.event("transfer.pull", "transfer",
                            bytes=int(size or 0), stripes=0, path="mapped",
                            gbps=0.0, admission_wait_ms=0.0)
        return loc
    size, _ = loc_meta(loc)
    cache: dict = {}

    def sink(total: int, is_error: bool) -> memoryview:
        tgt = cache.get("t")
        if tgt is not None:
            if tgt.size == total:
                return tgt.view  # retry attempt: overwrite in place
            tgt.abort()
        tgt = create_raw(oid, total)
        cache["t"] = tgt
        return tgt.view

    try:
        _, is_error = client.pull(addr, loc, into=sink, size_hint=size)
        return cache["t"].seal(is_error)
    except BaseException:
        tgt = cache.get("t")
        if tgt is not None:
            tgt.abort()
        raise


def free_local(loc: Location) -> None:
    """Physically delete a local (unwrapped) location's backing storage.

    Used by node agents when the head broadcasts a free for an object hosted
    on this agent's node."""
    kind = loc[0]
    if kind == "arena":
        try:
            _open_arena(loc[1]).delete(loc[2])
        # graftlint: allow[swallowed-exception] remote-free of a location its node may have already dropped
        except Exception:
            pass
    elif kind == "shm":
        name = loc[1]
        _segment_cache.drop(name)
        try:
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
    elif kind == "disk":
        try:
            os.remove(loc[1])
        except OSError:
            pass


class _SegmentCache:
    """Per-process cache of opened read-side segments.

    Deserialized arrays are zero-copy views over the mapping, so segments stay mapped
    until the process exits or the coordinator broadcasts a free.
    """

    def __init__(self):
        self._segs: Dict[str, shared_memory.SharedMemory] = {}
        self._lock = threading.Lock()

    def open(self, name: str) -> shared_memory.SharedMemory:
        with self._lock:
            seg = self._segs.get(name)
            if seg is None:
                seg = shared_memory.SharedMemory(name=name)
                self._segs[name] = seg
            return seg

    def drop(self, name: str) -> None:
        with self._lock:
            seg = self._segs.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except BufferError:
                _unclosable_segments.append(seg)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass


_segment_cache = _SegmentCache()
# segments whose mappings are pinned by live zero-copy views; kept referenced so
# SharedMemory.__del__ doesn't emit BufferError warnings during gc
_unclosable_segments: List[Any] = []


def resolve(loc: Location, oid: Optional[ObjectID] = None) -> Any:
    """Reconstruct the Python value at a location. Raises if it is an error object.

    When oid is given, a device-resident original in this process (jax.Array fast
    path, experimental/device_objects.py) is returned without deserializing."""
    if oid is not None:
        from ray_tpu.experimental import device_objects

        hit = device_objects.lookup(oid.binary())
        if hit is not None:
            return hit
    kind = loc[0]
    if kind == "inline":
        _, frame, is_error = loc
        value = serialization.loads(frame)
    elif kind == "arena":
        _, name, oid_bytes, size, is_error = loc
        arena = _open_arena(name)
        view = arena.get(oid_bytes)  # takes a reader pin
        if view is None:
            raise ObjectLost(f"arena object {oid_bytes.hex()} was freed or lost")
        value = serialization.deserialize_frame(view[:size])
        # Zero-copy views into the arena stay valid while the value lives: hold the
        # pin until the value is collected (plasma analog: client buffer refcount).
        # Roots that can't carry a finalizer (tuple/list/dict) get a private copy
        # instead, so the pin can drop immediately.
        try:
            import weakref

            weakref.finalize(value, arena.unpin, bytes(oid_bytes))
        except TypeError:
            copy = bytearray(view[:size])
            value = serialization.deserialize_frame(memoryview(copy))
            arena.unpin(oid_bytes)
    elif kind == "shm":
        _, name, size, is_error = loc
        try:
            seg = _segment_cache.open(name)
        except FileNotFoundError:
            raise ObjectLost(f"shm segment {name} was freed or lost") from None
        value = serialization.deserialize_frame(memoryview(seg.buf)[:size])
    elif kind == "disk":
        _, path, size, is_error = loc
        import mmap as _mmap

        try:
            with open(path, "rb") as f:
                m = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        except (FileNotFoundError, ValueError, OSError):
            raise ObjectLost(f"spilled object file {path} was lost") from None
        # zero-copy: deserialized arrays are views over the file mapping; the
        # exported buffer keeps the mmap alive until the views are collected
        value = serialization.deserialize_frame(memoryview(m)[:size])
    else:
        raise ValueError(f"unknown location kind {kind!r}")
    if is_error:
        raise value
    return value


def spill_location(loc: Location, spill_dir: str) -> Optional[Location]:
    """Move a sealed arena/shm object's bytes to a disk file, freeing the memory
    (reference LocalObjectManager::SpillObjects). Returns the new location, or
    None if the object cannot be spilled (inline/already-disk/lost)."""
    kind = loc[0]
    os.makedirs(spill_dir, exist_ok=True)
    if kind == "arena":
        _, name, oid_bytes, size, is_error = loc
        arena = _open_arena(name)
        view = arena.get(oid_bytes)  # reader pin
        if view is None:
            return None
        path = os.path.join(spill_dir, oid_bytes.hex())
        try:
            with open(path, "wb") as f:
                f.write(view[:size])
        finally:
            view.release()
            arena.unpin(oid_bytes)
        arena.delete(oid_bytes)
        return ("disk", path, size, is_error)
    if kind == "shm":
        _, name, size, is_error = loc
        try:
            seg = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            return None
        path = os.path.join(spill_dir, name)
        try:
            with open(path, "wb") as f:
                f.write(bytes(seg.buf[:size]))
            seg.unlink()  # removes the name; live mappings elsewhere stay valid
        finally:
            try:
                seg.close()
            except BufferError:
                # zero-copy views in this process keep the mapping alive; park the
                # handle so its __del__ doesn't warn at gc time
                _unclosable_segments.append(seg)
        _segment_cache.drop(name)
        return ("disk", path, size, is_error)
    return None


class ObjectStore:
    """Node-side coordinator: object directory, pending waits, refcounts, eviction."""

    def __init__(self):
        self._lock = threading.Lock()
        self._locations: Dict[ObjectID, Location] = {}  # insertion/touch order = LRU
        self._events: Dict[ObjectID, threading.Event] = {}
        self._refcounts: Dict[ObjectID, int] = {}
        self._failed: Dict[ObjectID, Exception] = {}
        self.on_free = None  # callback(oid) — cluster drops lineage entries
        # callback(loc) for ("remote", host, inner) locations — the cluster
        # forwards the free to the hosting node agent (multi-host plane)
        self.on_remote_free = None
        # callback(oid, old_loc) after spill_lru moves an object to disk:
        # adopted same-host-map replicas (pull_to_store shares the source's
        # mapping instead of copying) cache old_loc verbatim and must be
        # invalidated — the arena entry / segment name they point at is gone
        self.on_spill = None

    # -- directory -----------------------------------------------------------------
    def add(self, oid: ObjectID, loc: Location) -> None:
        with self._lock:
            self._locations[oid] = loc
            ev = self._events.pop(oid, None)
        if ev is not None:
            ev.set()

    def drop_location(self, oid: ObjectID) -> None:
        """Forget a lost location so lineage reconstruction can re-add it."""
        with self._lock:
            self._locations.pop(oid, None)

    def mark_failed(self, oid: ObjectID, err: Exception) -> None:
        with self._lock:
            self._failed[oid] = err
            ev = self._events.pop(oid, None)
        if ev is not None:
            ev.set()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._locations or oid in self._failed

    def location(self, oid: ObjectID, timeout: Optional[float] = None) -> Location:
        """Block until oid is available and return its location."""
        with self._lock:
            loc = self._locations.get(oid)
            if loc is not None:
                self._locations.pop(oid)  # LRU touch
                self._locations[oid] = loc
                return loc
            if oid in self._failed:
                raise self._failed[oid]
            ev = self._events.get(oid)
            if ev is None:
                ev = threading.Event()
                self._events[oid] = ev
        if not ev.wait(timeout):
            raise TimeoutError(f"timed out waiting for {oid!r}")
        with self._lock:
            if oid in self._failed:
                raise self._failed[oid]
            loc = self._locations[oid]
            # LRU touch for the spill policy
            self._locations.pop(oid)
            self._locations[oid] = loc
            return loc

    def try_location(self, oid: ObjectID) -> Optional[Location]:
        with self._lock:
            if oid in self._failed:
                raise self._failed[oid]
            return self._locations.get(oid)

    def wait(self, oids: List[ObjectID], num_returns: int, timeout: Optional[float]):
        """ray.wait semantics: first num_returns ready (by input order), rest not-ready."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectID] = []
        pending = list(oids)
        while True:
            still_pending = []
            for oid in pending:
                with self._lock:
                    done = oid in self._locations or oid in self._failed
                if done:
                    ready.append(oid)
                else:
                    still_pending.append(oid)
            pending = still_pending
            if len(ready) >= num_returns or not pending:
                return ready, pending
            if deadline is not None and time.monotonic() >= deadline:
                return ready, pending
            time.sleep(0.001)

    # -- lifetime ------------------------------------------------------------------
    def incref(self, oid: ObjectID, n: int = 1) -> None:
        with self._lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + n

    def decref(self, oid: ObjectID, n: int = 1) -> None:
        free = False
        with self._lock:
            c = self._refcounts.get(oid, 0) - n
            if c <= 0:
                self._refcounts.pop(oid, None)
                free = True
            else:
                self._refcounts[oid] = c
        if free:
            self._free(oid)

    def _free(self, oid: ObjectID) -> None:
        from ray_tpu.experimental import device_objects

        device_objects.drop(oid.binary())
        with self._lock:
            loc = self._locations.pop(oid, None)
            self._failed.pop(oid, None)
        if self.on_free is not None:
            try:
                self.on_free(oid)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass
        if loc is None:
            return
        if loc[0] == "remote":
            if self.on_remote_free is not None:
                try:
                    self.on_remote_free(loc)
                # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
                except Exception:
                    pass
        else:
            free_local(loc)

    def spill_lru(self, bytes_to_free: int, spill_dir: str) -> int:
        """Spill least-recently-used arena/shm objects until bytes_to_free memory
        bytes are on disk (reference LocalObjectManager::SpillObjectsOfSize).
        Returns bytes actually spilled."""
        with self._lock:
            candidates = [
                (oid, loc) for oid, loc in self._locations.items()
                if loc[0] in ("arena", "shm")
            ]
        spilled = 0
        for oid, loc in candidates:  # dict order = LRU (oldest first)
            if spilled >= bytes_to_free:
                break
            try:
                new_loc = spill_location(loc, spill_dir)
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                continue  # skip unspillable objects, keep relieving pressure
            if new_loc is None:
                continue
            # swap only if the object still lives at the snapshotted location:
            # a free() (refcount hit zero) or concurrent spill mid-write must not
            # leave an orphaned disk file counted as relieved memory
            with self._lock:
                swapped = self._locations.get(oid) == loc
                if swapped:
                    self._locations[oid] = new_loc
            if not swapped:
                try:
                    os.remove(new_loc[1])
                except OSError:
                    pass
                continue
            if self.on_spill is not None:
                try:
                    self.on_spill(oid, loc)
                # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
                except Exception:
                    pass
            spilled += new_loc[2]
        return spilled

    def memory_bytes(self) -> int:
        """Bytes resident in shared memory (arena + segments), i.e. spillable."""
        with self._lock:
            return sum(
                l[3] if l[0] == "arena" else l[2]
                for l in self._locations.values() if l[0] in ("arena", "shm")
            )

    def free_all(self) -> None:
        with self._lock:
            oids = list(self._locations.keys())
        for oid in oids:
            self._free(oid)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            shm_bytes = sum(l[2] for l in self._locations.values() if l[0] == "shm")
            arena_bytes = sum(l[3] for l in self._locations.values() if l[0] == "arena")
            inline_bytes = sum(len(l[1]) for l in self._locations.values() if l[0] == "inline")
            return {
                "num_objects": len(self._locations),
                "shm_bytes": shm_bytes,
                "arena_bytes": arena_bytes,
                "inline_bytes": inline_bytes,
            }
