"""Two-tier object store: inline bytes for small objects, POSIX shared memory for large.

Capability parity: reference plasma store (src/ray/object_manager/plasma/store.h:55) +
CoreWorker memory store (src/ray/core_worker/store_provider/). Differences by design:
- Producers (any process) create the shared-memory segment themselves and register only
  metadata with the node coordinator, so large task returns and puts never copy through a
  pipe (plasma's create/seal protocol, without a separate store daemon).
- Readers map segments zero-copy; numpy arrays deserialized from a segment are views over
  the mapping (pickle5 out-of-band buffers, see serialization.py).
"""
from __future__ import annotations

import threading
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .ids import ObjectID

# Objects below this many serialized bytes travel inline through control pipes.
INLINE_THRESHOLD = 100 * 1024

# Location tuples:  ("inline", frame_bytes, is_error) | ("shm", name, nbytes, is_error)
Location = Tuple


class ObjectLost(Exception):
    pass


def materialize(obj: Any, oid: ObjectID, is_error: bool = False) -> Location:
    """Serialize obj and place it: small -> inline bytes, large -> new shm segment."""
    ser = serialization.serialize(obj)
    size = ser.frame_bytes
    if size < INLINE_THRESHOLD:
        return ("inline", ser.to_bytes(), is_error)
    name = "rt_" + oid.hex()[:24]
    seg = shared_memory.SharedMemory(name=name, create=True, size=size)
    try:
        ser.write_into(seg.buf)
    finally:
        seg.close()
    return ("shm", name, size, is_error)


class _SegmentCache:
    """Per-process cache of opened read-side segments.

    Deserialized arrays are zero-copy views over the mapping, so segments stay mapped
    until the process exits or the coordinator broadcasts a free.
    """

    def __init__(self):
        self._segs: Dict[str, shared_memory.SharedMemory] = {}
        self._lock = threading.Lock()

    def open(self, name: str) -> shared_memory.SharedMemory:
        with self._lock:
            seg = self._segs.get(name)
            if seg is None:
                seg = shared_memory.SharedMemory(name=name)
                self._segs[name] = seg
            return seg

    def drop(self, name: str) -> None:
        with self._lock:
            seg = self._segs.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except Exception:
                pass


_segment_cache = _SegmentCache()


def resolve(loc: Location) -> Any:
    """Reconstruct the Python value at a location. Raises if it is an error object."""
    kind = loc[0]
    if kind == "inline":
        _, frame, is_error = loc
        value = serialization.loads(frame)
    elif kind == "shm":
        _, name, size, is_error = loc
        seg = _segment_cache.open(name)
        value = serialization.deserialize_frame(memoryview(seg.buf)[:size])
    else:
        raise ValueError(f"unknown location kind {kind!r}")
    if is_error:
        raise value
    return value


class ObjectStore:
    """Node-side coordinator: object directory, pending waits, refcounts, eviction."""

    def __init__(self):
        self._lock = threading.Lock()
        self._locations: Dict[ObjectID, Location] = {}
        self._events: Dict[ObjectID, threading.Event] = {}
        self._refcounts: Dict[ObjectID, int] = {}
        self._failed: Dict[ObjectID, Exception] = {}

    # -- directory -----------------------------------------------------------------
    def add(self, oid: ObjectID, loc: Location) -> None:
        with self._lock:
            self._locations[oid] = loc
            ev = self._events.pop(oid, None)
        if ev is not None:
            ev.set()

    def mark_failed(self, oid: ObjectID, err: Exception) -> None:
        with self._lock:
            self._failed[oid] = err
            ev = self._events.pop(oid, None)
        if ev is not None:
            ev.set()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._locations or oid in self._failed

    def location(self, oid: ObjectID, timeout: Optional[float] = None) -> Location:
        """Block until oid is available and return its location."""
        with self._lock:
            loc = self._locations.get(oid)
            if loc is not None:
                return loc
            if oid in self._failed:
                raise self._failed[oid]
            ev = self._events.get(oid)
            if ev is None:
                ev = threading.Event()
                self._events[oid] = ev
        if not ev.wait(timeout):
            raise TimeoutError(f"timed out waiting for {oid!r}")
        with self._lock:
            if oid in self._failed:
                raise self._failed[oid]
            return self._locations[oid]

    def try_location(self, oid: ObjectID) -> Optional[Location]:
        with self._lock:
            if oid in self._failed:
                raise self._failed[oid]
            return self._locations.get(oid)

    def wait(self, oids: List[ObjectID], num_returns: int, timeout: Optional[float]):
        """ray.wait semantics: first num_returns ready (by input order), rest not-ready."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectID] = []
        pending = list(oids)
        while True:
            still_pending = []
            for oid in pending:
                with self._lock:
                    done = oid in self._locations or oid in self._failed
                if done:
                    ready.append(oid)
                else:
                    still_pending.append(oid)
            pending = still_pending
            if len(ready) >= num_returns or not pending:
                return ready, pending
            if deadline is not None and time.monotonic() >= deadline:
                return ready, pending
            time.sleep(0.001)

    # -- lifetime ------------------------------------------------------------------
    def incref(self, oid: ObjectID, n: int = 1) -> None:
        with self._lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + n

    def decref(self, oid: ObjectID, n: int = 1) -> None:
        free = False
        with self._lock:
            c = self._refcounts.get(oid, 0) - n
            if c <= 0:
                self._refcounts.pop(oid, None)
                free = True
            else:
                self._refcounts[oid] = c
        if free:
            self._free(oid)

    def _free(self, oid: ObjectID) -> None:
        with self._lock:
            loc = self._locations.pop(oid, None)
            self._failed.pop(oid, None)
        if loc is not None and loc[0] == "shm":
            name = loc[1]
            _segment_cache.drop(name)
            try:
                seg = shared_memory.SharedMemory(name=name)
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass
            except Exception:
                pass

    def free_all(self) -> None:
        with self._lock:
            oids = list(self._locations.keys())
        for oid in oids:
            self._free(oid)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            shm_bytes = sum(l[2] for l in self._locations.values() if l[0] == "shm")
            inline_bytes = sum(len(l[1]) for l in self._locations.values() if l[0] == "inline")
            return {
                "num_objects": len(self._locations),
                "shm_bytes": shm_bytes,
                "inline_bytes": inline_bytes,
            }
