"""Per-host node agent: joins a remote head over TCP and hosts workers locally.

Capability parity: reference raylet daemon (src/ray/raylet/node_manager.h:124 —
worker pool + local object management on each host, registered with the GCS,
src/ray/gcs/gcs_server/gcs_node_manager.h:49). The head (core/node.py Cluster)
keeps all scheduling/ownership state; this agent is deliberately thin:

- registers its resources with the head and heartbeats;
- spawns/kills local worker processes on request, relaying every worker pipe
  message to/from the head verbatim (workers are unchanged — their pipe simply
  terminates at the agent, which forwards over one TCP connection);
- owns this host's shared-memory arena and serves raw object fetch/store/free
  requests for the cross-host transfer path (reference object_manager.h:119).

Transport is a TYPED gRPC bidirectional stream (protos/node_agent.proto;
reference src/ray/rpc/ + node_manager.proto): the per-cluster session authkey
rides the stream metadata, control messages are protobuf (the head never
unpickles agent traffic), and only opaque worker-pipe frames remain pickled —
they originate and terminate inside the head's own trust domain.

Run with `ray-tpu start --address=HOST:PORT` (scripts/cli.py) or spawn
`python -m ray_tpu.core.node_agent --address HOST:PORT` directly.
"""
from __future__ import annotations

import collections
import json
import multiprocessing
import multiprocessing.connection
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

_mp = multiprocessing.get_context("spawn")

from ray_tpu.config import CONFIG
from ray_tpu.core.exceptions import FaultInjectedError
from ray_tpu.util import fault_injection


class NodeAgent:
    def __init__(self, head_host: str, head_port: int, authkey: bytes,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 max_workers: Optional[int] = None,
                 fallback_addresses: Optional[list] = None):
        from .resources import normalize_resources

        if resources is None:
            num_cpus = (CONFIG.num_cpus if CONFIG.num_cpus is not None
                        else float(os.cpu_count() or 1))
            detected: Dict[str, float] = {}
            env_tpus = CONFIG.num_tpus
            if env_tpus is not None:
                num_tpus = env_tpus
            else:
                from .accelerators import TPUAcceleratorManager

                detected = TPUAcceleratorManager.node_resources()
                num_tpus = detected.pop("TPU", 0.0)
            resources = normalize_resources(num_cpus=num_cpus, num_tpus=num_tpus,
                                            resources=None)
            for k, v in detected.items():
                resources.setdefault(k, v)
        self.resources = resources
        self.labels = labels or {}
        self.max_workers = max_workers or CONFIG.max_workers_per_node
        self._head_host = head_host
        self._head_port = head_port
        # replacement-head candidates (HA: an external-store journal lets the
        # head restart on a different machine/port; reference
        # gcs_redis_failure_detector.h — raylets reconnect to wherever GCS
        # comes back). Reconnect cycles current + fallbacks.
        self._head_addresses = [(head_host, head_port)] + list(fallback_addresses or [])
        self._authkey = authkey
        # typed gRPC control stream (reference node_manager.proto): tuples
        # encode to protobuf at the boundary, nothing is pickled on this channel
        from . import agent_rpc

        # initial dial tries every candidate: an agent (re)started AFTER a head
        # failover must be able to join the replacement directly
        last_err: Optional[Exception] = None
        self.conn = None
        for host, port in self._head_addresses:
            try:
                self.conn = agent_rpc.HeadConnection(host, port, authkey)
                self._head_host, self._head_port = host, port
                break
            except Exception as e:  # noqa: BLE001 — try the next candidate
                last_err = e
        if self.conn is None:
            raise last_err if last_err else OSError("no head address reachable")
        # bulk-object plane: a dedicated listener (chunked pulls from peers /
        # the head) + a pooled puller, so object bytes never ride the control
        # connection (reference object_manager.h:119)
        from . import data_plane, object_store

        # read_pinned_any: served chunk frames are pinned views of the local
        # shm/arena mapping, never a per-pull copy
        self._data_server = data_plane.DataServer(
            authkey, object_store.read_pinned_any)
        self._data_client = data_plane.DataClient(authkey)
        self._send_lock = threading.Lock()
        self._workers: Dict[str, Any] = {}   # wid_hex -> (proc, pipe)
        self._pipe_to_wid: Dict[Any, str] = {}
        self._shutdown = False
        self._dead_worker_logs: Dict[str, float] = {}  # wid -> death time (log grace)
        self._wakeup_r, self._wakeup_w = _mp.Pipe(duplex=False)
        self.worker_env: Dict[str, str] = {}
        self.node_id_hex: Optional[str] = None
        # observability pre-aggregation (PR 17): instead of relaying every
        # worker's metrics/telemetry push to the head, intercept them here,
        # merge, and ship ONE per-node delta per flush tick — head-side
        # scrape cost becomes O(nodes). Gated by RAY_TPU_CONTROL_NODE_AGG
        # (off = verbatim relay, the head's automatic fallback path).
        self._agg_lock = threading.Lock()
        self._agg_metrics: Dict[str, list] = {}  # wid_hex -> latest snapshot
        self._agg_telemetry: "collections.deque" = collections.deque(maxlen=256)
        self._agg_seq = 0
        self._agg_thread: Optional[threading.Thread] = None
        # head-imposed minimum flush interval (typed backpressure signal);
        # 0.0 = no backpressure, agent runs at its own cadence
        self._bp_min_interval_s = 0.0
        # loss-intolerant relay frames (task results, worker decrefs,
        # collective joins) whose send failed during a head outage: queued
        # here and replayed IN ORDER after reregister. Only frames that never
        # left this process are queued, so replay is exactly-once.
        self._relay_lock = threading.Lock()
        self._pending_relay: "collections.deque" = collections.deque()

    # -- transport ----------------------------------------------------------------
    def _send(self, msg) -> None:
        fault_injection.fail_point("head.control.send",
                                   kind=msg[0] if msg else None)
        with self._send_lock:
            self.conn.send(msg)

    # -- lifecycle ----------------------------------------------------------------
    def register(self) -> None:
        self._send(("register", self.resources, self.labels, self.max_workers,
                    {"data_port": self._data_server.port}))
        kind, payload = self.conn.recv()
        assert kind == "welcome", kind
        self.node_id_hex = payload["node_id"]
        self.worker_env = dict(payload.get("worker_env") or {})
        default_renv = payload.get("default_runtime_env")
        if default_renv:
            # reconcile the job-level runtime env on join: build this host's
            # pip/uv overlays before the first task needs them (reference:
            # per-node runtime-env agent materializing envs at job start)
            def _prewarm():
                try:
                    from ray_tpu.runtime_env import prewarm

                    prewarm(default_renv)
                except Exception as e:
                    import logging

                    logging.getLogger("ray_tpu.node_agent").warning(
                        "runtime-env prewarm failed: %s", e)

            threading.Thread(target=_prewarm, daemon=True,
                             name="agent-renv-prewarm").start()
        store_bytes = int(payload.get("object_store_memory") or 0)
        from . import object_store

        # this host's own arena: never share arena names across hosts — the
        # head wraps this host's locations as ("remote", node_id, inner)
        self.worker_env.pop(object_store._ARENA_ENV, None)
        os.environ.pop(object_store._ARENA_ENV, None)
        if store_bytes > 0:
            arena_name = object_store.init_arena(store_bytes)
            if arena_name:
                self.worker_env[object_store._ARENA_ENV] = arena_name

    def serve_forever(self) -> None:
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name="agent-heartbeat")
        hb.start()
        threading.Thread(target=self._tail_logs_loop, daemon=True,
                         name="agent-log-tail").start()
        try:
            self._serve_loop()
        finally:
            self._shutdown = True
            self._kill_all_workers()
            self._data_server.close()
            self._data_client.close()
            try:
                self.conn.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            from . import object_store

            object_store.destroy_arena()

    @property
    def _log_dir(self) -> str:
        return os.path.join(CONFIG.session_dir, "logs",
                            (self.node_id_hex or "node")[:12])

    def _tail_logs_loop(self) -> None:
        """Stream appended worker stdout/stderr lines to the head (reference
        log_monitor.py:105 tailing worker logs to the driver). Dead workers
        keep being tailed for a grace period — a crash's final traceback is
        exactly the output that must not be dropped."""
        offsets: Dict[tuple, int] = {}
        pending: Dict[tuple, bytes] = {}  # trailing partial line per file
        while not self._shutdown:
            now = time.monotonic()
            # mutate in place: a death recorded by the serve-loop thread
            # between a snapshot and a dict REASSIGNMENT would be lost (and
            # with it the crash traceback the grace period exists for)
            for wid, t in list(self._dead_worker_logs.items()):
                if now - t >= 10.0:
                    self._dead_worker_logs.pop(wid, None)
            wids = set(self._workers) | set(self._dead_worker_logs)
            for key in list(offsets):
                if key[0] not in wids:
                    offsets.pop(key, None)  # drained + grace passed
                    pending.pop(key, None)
            for wid in wids:
                for stream in ("out", "err"):
                    key = (wid, stream)
                    path = os.path.join(self._log_dir, f"worker-{wid}.{stream}")
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue
                    off = offsets.get(key, 0)
                    while off < size:  # drain the whole backlog this pass
                        try:
                            with open(path, "rb") as f:
                                f.seek(off)
                                chunk = f.read(min(size - off, 65536))
                        except OSError:
                            break
                        if not chunk:
                            break
                        off += len(chunk)
                        offsets[key] = off
                        # forward COMPLETE lines only: a line (or multi-byte
                        # codepoint) straddling the read boundary must not be
                        # split into two messages / mangled to U+FFFD
                        data = pending.pop(key, b"") + chunk
                        complete, nl, rest = data.rpartition(b"\n")
                        if nl:
                            self._send_log(wid, stream, complete + b"\n")
                        if rest:
                            pending[key] = rest
                    if (pending.get(key) and off >= size
                            and wid not in self._workers):
                        # dead worker fully drained: flush its unterminated tail
                        self._send_log(wid, stream, pending.pop(key))
            time.sleep(0.5)

    def _send_log(self, wid: str, stream: str, data: bytes) -> None:
        try:
            self._send(("worker_log", wid, stream, data.decode(errors="replace")))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass  # head restart in progress: this chunk is lost

    def _heartbeat_loop(self) -> None:
        while not self._shutdown:
            try:
                self._send(("heartbeat", time.time()))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass  # head restart in progress: resume on the new connection
            time.sleep(CONFIG.agent_heartbeat_s)

    def _serve_loop(self) -> None:
        """Relay worker pipes; head messages arrive on the gRPC recv thread."""
        threading.Thread(target=self._head_recv_loop, daemon=True,
                         name="agent-head-recv").start()
        while not self._shutdown:
            pipes = list(self._pipe_to_wid.keys())
            ready = multiprocessing.connection.wait(
                [self._wakeup_r] + pipes, timeout=1.0)
            for c in ready:
                if c is self._wakeup_r:
                    try:
                        self._wakeup_r.recv_bytes()
                    # graftlint: allow[swallowed-exception] peer closed mid-recv; the loop exits via its own stop flag
                    except Exception:
                        pass
                    continue
                wid = self._pipe_to_wid.get(c)
                if wid is None:
                    continue
                try:
                    raw = c.recv_bytes()
                except (EOFError, OSError):
                    self._on_local_worker_death(wid)
                    continue
                if self._maybe_aggregate(wid, raw):
                    continue
                try:
                    self._send(("from_worker", wid, raw))
                # graftlint: allow[swallowed-exception] loss-intolerant frame queued for replay, not dropped
                except Exception:  # noqa: BLE001 — head restart in flight
                    # loss-intolerant frame (a task result, a decref, a
                    # collective join): queue it for in-order replay once the
                    # reconnect loop re-registers with the restarted head
                    self._queue_relay(wid, raw)

    def _queue_relay(self, wid: str, raw: bytes) -> None:
        """Buffer a worker frame that failed to send (head outage) for replay
        after reregister. Bounded by RAY_TPU_HEAD_OUTBOX_LIMIT: past it the
        OLDEST frames fall off with a throttled warning — an unbounded queue
        under a long outage would OOM the agent, which is strictly worse."""
        limit = CONFIG.head_outbox_limit
        with self._relay_lock:
            self._pending_relay.append((wid, raw))
            dropped = 0
            while limit > 0 and len(self._pending_relay) > limit:
                self._pending_relay.popleft()
                dropped += 1
        if dropped:
            import logging

            logging.getLogger("ray_tpu.node_agent").warning(
                "head-outage relay outbox overflowed: dropped %d oldest "
                "frame(s) (limit %d)", dropped, limit)

    # -- observability pre-aggregation ----------------------------------------------

    # cloudpickle protocol-5 markers for the two frame kinds we intercept:
    # ("metrics", ...) / ("telemetry", ...) tuples always carry their kind
    # string as SHORT_BINUNICODE within the first ~16 bytes. A cheap
    # substring prefilter avoids unpickling the hot task-result frames; a
    # false negative merely relays the frame per-worker (correct, just not
    # aggregated). Unpickling HERE is in-trust-domain: these frames come
    # from worker processes this agent itself spawned.
    _METRICS_MARK = b"\x8c\x07metrics\x94"
    _TELEMETRY_MARK = b"\x8c\ttelemetry\x94"

    def _maybe_aggregate(self, wid: str, raw: bytes) -> bool:
        """Absorb a worker's metrics/telemetry push into the node-local
        aggregate instead of relaying it. Returns False (relay verbatim)
        when aggregation is off or the frame is anything else."""
        if not CONFIG.control_node_agg:
            return False
        head = raw[:24]
        is_metrics = self._METRICS_MARK in head
        if not is_metrics and self._TELEMETRY_MARK not in head:
            return False
        try:
            msg = pickle.loads(raw)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:
            return False
        if not (isinstance(msg, tuple) and len(msg) >= 2):
            return False
        if msg[0] == "metrics":
            with self._agg_lock:
                # latest CUMULATIVE snapshot per worker: merging fresh copies
                # each flush keeps counter sums exact across flush ticks
                self._agg_metrics[wid] = msg[1]
        elif msg[0] == "telemetry":
            batch = msg[1] if isinstance(msg[1], dict) else {"events": msg[1]}
            with self._agg_lock:
                self._agg_telemetry.append({"wid": wid, **batch})
        else:
            return False
        self._ensure_agg_thread()
        return True

    def _ensure_agg_thread(self) -> None:
        if self._agg_thread is not None:
            return
        t = threading.Thread(target=self._node_flush_loop, daemon=True,
                             name="agent-node-flush")
        self._agg_thread = t
        t.start()

    def _node_flush_loop(self) -> None:
        """Ship one merged NodeMetrics delta per flush tick. The effective
        interval is max(own knob, head's backpressure minimum) — under inlet
        pressure the head widens everyone's cadence instead of dropping
        frames silently."""
        while not self._shutdown:
            interval = max(CONFIG.control_node_flush_s, self._bp_min_interval_s)
            time.sleep(max(0.05, interval))
            try:
                self._flush_node_delta(interval)
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass  # head restart in flight: next tick retries

    def _flush_node_delta(self, interval: float) -> None:
        from ray_tpu.util import metrics as _m

        with self._agg_lock:
            snaps = list(self._agg_metrics.values())
            worker_count = len(self._agg_metrics)
            tel = list(self._agg_telemetry)
            self._agg_telemetry.clear()
        if not snaps and not tel:
            return
        merged = _m.merge_snapshots(snaps)
        metrics_json = json.dumps(
            _m.snapshot_to_wire(list(merged.values()))).encode()
        # telemetry attrs may hold arbitrary values; default=str keeps the
        # delta JSON-clean without dropping the event
        telemetry_json = json.dumps(tel, default=str).encode()
        self._agg_seq += 1
        self._send(("node_metrics", self._agg_seq, time.time(), worker_count,
                    metrics_json, telemetry_json, interval))

    def _head_recv_loop(self) -> None:
        while not self._shutdown:
            try:
                fault_injection.fail_point("head.control.recv")
                msg = self.conn.recv()
            except (EOFError, FaultInjectedError):
                # head is gone (or a chaos fail point simulated exactly that):
                # hold workers alive and try to rejoin a restarted head
                # (reference: raylets buffering through a GCS restart,
                # NotifyGCSRestart / node_manager.proto:316)
                if self._shutdown:
                    return
                if self._reconnect():
                    continue
                self._shutdown = True  # reconnect window passed: workers die
                try:
                    self._wakeup_w.send_bytes(b"x")
                # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                except Exception:
                    pass
                return
            try:
                self._handle_head_message(msg)
            except Exception:
                import traceback

                traceback.print_exc()

    # -- head-restart recovery ------------------------------------------------------
    def _reconnect(self) -> bool:
        """Redial the head with backoff and re-register this node's live state
        (same node id, workers, arena contents). Workers stay up the whole
        time — their pipe messages queue in OS buffers until the relay resumes.
        Returns False when agent_reconnect_timeout_s passes."""
        try:
            self.conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        deadline = time.monotonic() + CONFIG.agent_reconnect_timeout_s
        delay = 0.3
        from . import agent_rpc

        attempt = 0
        while not self._shutdown and time.monotonic() < deadline:
            # round-robin over candidate heads: with a URI journal the
            # replacement head may come back on a different address
            host, port = self._head_addresses[attempt % len(self._head_addresses)]
            attempt += 1
            try:
                conn = agent_rpc.HeadConnection(
                    host, port, self._authkey,
                    connect_timeout=min(5.0, delay * 4))
            # graftlint: allow[swallowed-exception] redial loop: failures retry with backoff until the reconnect deadline
            except Exception:
                if attempt % len(self._head_addresses) == 0:
                    time.sleep(min(delay, max(0.05, deadline - time.monotonic())))
                    delay = min(delay * 2, 3.0)
                continue
            try:
                self._reregister(conn)
                self._head_host, self._head_port = host, port
                return True
            # graftlint: allow[swallowed-exception] redial loop: failures retry with backoff until the reconnect deadline
            except Exception:
                try:
                    conn.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
                time.sleep(delay)
        return False

    def _reregister(self, conn) -> None:
        from . import object_store

        arena = object_store._default_arena()
        objects = []
        arena_name = None
        if arena is not None:
            arena_name = arena.name
            from .ids import ObjectID

            objects = [(oid20[:ObjectID.SIZE], size, flags)
                       for oid20, size, flags in arena.list_sealed()]
        workers = [(wid, entry[2]) for wid, entry in self._workers.items()]
        msg = ("reregister", self.node_id_hex, self.resources, self.labels,
               self.max_workers,
               {"data_port": self._data_server.port, "arena": arena_name,
                "workers": workers, "objects": objects})
        # first send BEFORE the swap: the heartbeat thread must not slip a
        # ("heartbeat", ts) in as the new stream's first message — the head
        # treats the first frame as the (re)register handshake
        conn.send(msg)
        kind, payload = conn.recv()
        assert kind == "welcome_back", kind
        with self._send_lock:
            self.conn = conn
        # the restarted head kept only the workers it could rebind (journaled
        # detached/named actors); the rest ran tasks whose callers died with
        # the old head — kill them so their results don't relay into a void
        keep = set(payload.get("keep_workers") or ())
        for wid in list(self._workers):
            if wid not in keep:
                entry = self._workers.get(wid)
                try:
                    entry[0].terminate()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
        # replay the outage's loss-intolerant relay backlog IN ORDER, kept
        # workers only (a killed worker's results relay into a void anyway)
        with self._relay_lock:
            backlog = list(self._pending_relay)
            self._pending_relay.clear()
        for wid, raw in backlog:
            if wid not in keep:
                continue
            try:
                self._send(("from_worker", wid, raw))
            # graftlint: allow[swallowed-exception] re-queued for the next reconnect's replay, not dropped
            except Exception:  # noqa: BLE001 — outage resumed mid-replay
                self._queue_relay(wid, raw)
        # tell surviving workers the head restarted: replies to requests sent
        # on the OLD head are gone forever — the worker fails those pending
        # slots with a typed HeadUnavailableError instead of hanging
        note = cloudpickle.dumps(("head_restarted", time.time()))
        for wid in list(self._workers):
            if wid not in keep:
                continue
            entry = self._workers.get(wid)
            if entry is None:
                continue
            try:
                entry[1].send_bytes(note)
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass

    # -- head messages --------------------------------------------------------------
    def _handle_head_message(self, msg) -> None:
        kind = msg[0]
        if kind == "spawn_worker":
            _, wid_hex, accel = msg[:3]
            extra_env = msg[3] if len(msg) > 3 else None
            container = msg[4] if len(msg) > 4 else None
            self._spawn_worker(wid_hex, accel, extra_env, container)
        elif kind == "to_worker":
            _, wid_hex, raw = msg
            entry = self._workers.get(wid_hex)
            if entry is not None:
                try:
                    entry[1].send_bytes(raw)
                except (OSError, BrokenPipeError):
                    self._on_local_worker_death(wid_hex)
        elif kind == "kill_worker":
            _, wid_hex = msg
            entry = self._workers.get(wid_hex)
            if entry is not None:
                try:
                    entry[0].terminate()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
        elif kind == "req":
            _, req_id, op, args = msg
            # object-plane requests run on their own thread: an arena read must
            # never stall worker-pipe relaying
            threading.Thread(target=self._serve_req, args=(req_id, op, args),
                             daemon=True, name=f"agent-{op}").start()
        elif kind == "free_object":
            from . import object_store

            object_store.free_local(msg[1])
        elif kind == "control_backpressure":
            _, level, min_interval_s = msg
            new = float(min_interval_s) if level > 0 else 0.0
            if new != self._bp_min_interval_s:
                import logging

                logging.getLogger("ray_tpu.node_agent").info(
                    "head backpressure level=%d: node flush interval >= %.1fs",
                    level, new)
            self._bp_min_interval_s = new
        elif kind == "shutdown":
            self._shutdown = True

    def _serve_req(self, req_id: int, op: str, args: tuple) -> None:
        from . import object_store

        try:
            if op == "fetch_object":
                (loc,) = args
                value = object_store.read_raw(loc)
            elif op == "store_object":
                oid, data, is_error = args
                value = object_store.write_raw(data, oid, is_error)
            elif op == "pull_object":
                # direct transfer: fetch straight from the source node's data
                # server (the head only brokered the location), store locally.
                # A None host means "the head itself" — substitute the address
                # this agent already dials for control traffic.
                oid, src_loc, src_addr = args
                if src_addr[0] is None:
                    src_addr = (self._head_host, src_addr[1])
                # striped zero-copy pull: bytes land directly in this node's
                # pre-created arena/shm backing and seal in place
                value = object_store.pull_to_store(
                    self._data_client, src_addr, src_loc, oid)
            elif op == "gc_dead_owners":
                (keep,) = args
                arena = object_store._default_arena()
                if arena is not None:
                    arena.gc_dead_owners(keep)
                value = True
            else:
                raise ValueError(f"unknown agent op {op!r}")
            ok = True
        except BaseException as e:  # noqa: BLE001
            ok, value = False, e
        try:
            self._send(("reply", req_id, ok, value))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    # -- worker pool -----------------------------------------------------------------
    def _spawn_worker(self, wid_hex: str, accel: str,
                      extra_env: Optional[Dict[str, str]] = None,
                      container: Optional[Dict] = None) -> None:
        from .worker import worker_main

        if container is not None:
            self._spawn_container_worker(wid_hex, accel, extra_env, container)
            return
        parent_conn, child_conn = _mp.Pipe(duplex=True)
        env = dict(self.worker_env)
        if extra_env:  # runtime_env env_vars applied at process spawn
            env.update(extra_env)
        env["RAY_TPU_WORKER_LOG_DIR"] = self._log_dir
        proc = _mp.Process(
            target=worker_main,
            args=(child_conn, self.node_id_hex, wid_hex, accel, env),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        self._workers[wid_hex] = (proc, parent_conn, accel)
        self._pipe_to_wid[parent_conn] = wid_hex
        try:
            self._wakeup_w.send_bytes(b"x")
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def _spawn_container_worker(self, wid_hex: str, accel: str,
                                extra_env: Optional[Dict[str, str]],
                                container: Dict) -> None:
        """Agent-side container worker (runtime_env container/image_uri): same
        shared dial-back sequence as the head node (core/container.py), with
        the connection spliced into the agent's normal worker relay. Sends
        buffer in a PendingConn until the container dials back."""
        from . import container as _ctr

        env = dict(self.worker_env)
        if extra_env:
            env.update(extra_env)
        env["RAY_TPU_WORKER_LOG_DIR"] = self._log_dir
        pending = _ctr.PendingConn()
        entry_ready = threading.Event()

        def on_attach(conn) -> None:
            entry_ready.wait(timeout=30)
            pending.attach(conn)
            # recv side joins the relay loop on the REAL conn (fileno needed)
            entry = self._workers.get(wid_hex)
            if entry is None:  # killed while dialing back
                conn.close()
                return
            self._workers[wid_hex] = (entry[0], conn, accel)
            self._pipe_to_wid[conn] = wid_hex
            try:
                self._wakeup_w.send_bytes(b"x")
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass

        def on_fail(err) -> None:
            entry_ready.wait(timeout=30)
            # head sees the worker die in "starting" and fails/retries the task
            self._on_local_worker_death(wid_hex)

        try:
            proc = _ctr.spawn_with_dialback(
                container, self.node_id_hex, wid_hex, accel, env,
                on_attach, on_fail)
        except _ctr.ContainerRuntimeError:
            self._on_local_worker_death(wid_hex)
            return
        self._workers[wid_hex] = (proc, pending, accel)
        entry_ready.set()

    def _on_local_worker_death(self, wid_hex: str) -> None:
        self._dead_worker_logs[wid_hex] = time.monotonic()
        with self._agg_lock:
            self._agg_metrics.pop(wid_hex, None)
        entry = self._workers.pop(wid_hex, None)
        if entry is not None:
            self._pipe_to_wid.pop(entry[1], None)
            try:
                entry[1].close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        try:
            self._send(("worker_death", wid_hex))
        # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
        except Exception:
            pass

    def _kill_all_workers(self) -> None:
        for entry in list(self._workers.values()):
            try:
                entry[1].send_bytes(cloudpickle.dumps(("exit",)))
            # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for entry in list(self._workers.values()):
            proc = entry[0]
            proc.join(timeout=max(0.05, deadline - time.monotonic()))
            if proc.is_alive():
                proc.terminate()
        self._workers.clear()
        self._pipe_to_wid.clear()


def agent_main(address: str, authkey: Optional[bytes] = None,
               resources: Optional[Dict[str, float]] = None,
               labels: Optional[Dict[str, str]] = None,
               max_workers: Optional[int] = None) -> None:
    """Blocking entry point: join the head at address ("host:port") and serve."""
    import signal

    # SIGTERM (autoscaler scale-down, ray-tpu stop) must unwind serve_forever's
    # finally: otherwise worker children orphan and the shm arena never unlinks
    try:
        signal.signal(signal.SIGTERM, lambda *_: (_ for _ in ()).throw(SystemExit(0)))
    except ValueError:
        pass  # not the main thread (embedded use): caller owns signals
    if authkey is None:
        from ray_tpu.util.client.server import load_authkey

        authkey = load_authkey()
        if authkey is None:
            raise RuntimeError(
                "no cluster authkey: set RAY_TPU_CLIENT_AUTHKEY or run on a host "
                "with the head's session dir")
    candidates = []
    for addr in address.split(","):
        host, _, port = addr.strip().rpartition(":")
        candidates.append((host or "127.0.0.1", int(port)))
    agent = NodeAgent(candidates[0][0], candidates[0][1], authkey,
                      resources=resources, labels=labels, max_workers=max_workers,
                      fallback_addresses=candidates[1:])
    agent.register()
    agent.serve_forever()


def main(argv: Optional[list] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="ray_tpu node agent")
    p.add_argument("--address", required=True, help="head node-server host:port")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--max-workers", type=int, default=None)
    p.add_argument("--label", action="append", default=[],
                   help="k=v node label (repeatable; e.g. autoscaler instance ids)")
    args = p.parse_args(argv)
    if any("=" not in kv for kv in args.label):
        p.error("--label must be k=v")
    labels = dict(kv.split("=", 1) for kv in args.label)
    resources = None
    if args.num_cpus is not None or args.num_tpus is not None:
        from .resources import normalize_resources

        resources = normalize_resources(
            num_cpus=args.num_cpus if args.num_cpus is not None else
            float(os.cpu_count() or 1),
            num_tpus=args.num_tpus or 0.0, resources=None)
    agent_main(args.address, resources=resources, labels=labels or None,
               max_workers=args.max_workers)


if __name__ == "__main__":
    main()
