"""Prometheus + Grafana provisioning files for the cluster's metrics plane.

Capability parity: reference python/ray/dashboard/modules/metrics/ — on head
start it writes a ready-to-run `prometheus.yml` scraping every node's metrics
endpoint plus Grafana provisioning configs (datasource + dashboards dir) and
the default Grafana dashboard JSONs, so `prometheus --config.file=...` and
`grafana-server --config ...` come up pre-wired. Same contract here: one call
writes the whole tree under <session_dir>/metrics and returns the root.

    ray-tpu metrics launch-config   # CLI entry; prints the generated paths
"""
from __future__ import annotations

import json
import os
from typing import List, Optional


def _panel(panel_id: int, title: str, expr: str, y: int, unit: str = "short") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": "ray-tpu-prometheus",
        "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [{"expr": expr, "legendFormat": "{{__name__}}"}],
    }


def default_dashboard() -> dict:
    """The default cluster dashboard (reference: default_grafana_dashboard.json
    from dashboard/modules/metrics/dashboards) over our exported series."""
    rows = [
        ("Nodes", "ray_tpu_cluster_nodes", "short"),
        ("Workers", "ray_tpu_cluster_workers", "short"),
        ("Live actors", "ray_tpu_cluster_actors", "short"),
        ("Pending tasks", "ray_tpu_cluster_pending_tasks", "short"),
        ("Object store: objects", "ray_tpu_object_store_num_objects", "short"),
        ("Object store: arena bytes", "ray_tpu_object_store_arena_bytes", "bytes"),
        ("Object store: shm bytes", "ray_tpu_object_store_shm_bytes", "bytes"),
        ("LLM: generated tokens", "ray_tpu_llm_total_generated", "short"),
        ("LLM: KV pool occupancy", "ray_tpu_llm_kv_pool_occupancy", "percentunit"),
        ("LLM: preemptions", "ray_tpu_llm_num_preemptions", "short"),
        ("User metrics (ray_tpu_*)", '{__name__=~"ray_tpu_.+"}', "short"),
    ]
    panels = [
        _panel(i, title, expr, (i // 2) * 8, unit)
        for i, (title, expr, unit) in enumerate(rows)
    ]
    return {
        "title": "ray-tpu cluster",
        "uid": "ray-tpu-default",
        "timezone": "browser",
        "refresh": "10s",
        "schemaVersion": 39,
        "panels": panels,
        "time": {"from": "now-30m", "to": "now"},
    }


def provision(session_dir: Optional[str] = None,
              scrape_targets: Optional[List[str]] = None) -> str:
    """Write prometheus.yml + Grafana provisioning under <session_dir>/metrics.

    scrape_targets defaults to the local dashboard's /metrics endpoint; a
    multi-host head passes every agent's exporter address.
    """
    from ray_tpu.config import CONFIG

    root = os.path.join(session_dir or CONFIG.session_dir, "metrics")
    targets = scrape_targets or [f"127.0.0.1:{CONFIG.dashboard_port}"]

    prom_dir = os.path.join(root, "prometheus")
    os.makedirs(prom_dir, exist_ok=True)
    scrape: dict = {
        "job_name": "ray-tpu",
        "metrics_path": "/metrics",
        "static_configs": [{"targets": targets}],
    }
    if CONFIG.serve_ingress_tls:
        # the dashboard serves only TLS under this flag: scrape https and
        # verify against the cluster CA (certs carry IP SANs, not hostnames)
        scrape["scheme"] = "https"
        if CONFIG.tls_ca:
            scrape["tls_config"] = {"ca_file": CONFIG.tls_ca,
                                    "insecure_skip_verify": False}
    prom = {
        "global": {"scrape_interval": "10s", "evaluation_interval": "10s"},
        "scrape_configs": [scrape],
    }
    # prometheus reads YAML; this subset of YAML is exactly JSON
    with open(os.path.join(prom_dir, "prometheus.yml"), "w") as f:
        json.dump(prom, f, indent=2)

    graf_dir = os.path.join(root, "grafana")
    dash_dir = os.path.join(graf_dir, "dashboards")
    prov_ds = os.path.join(graf_dir, "provisioning", "datasources")
    prov_db = os.path.join(graf_dir, "provisioning", "dashboards")
    for d in (dash_dir, prov_ds, prov_db):
        os.makedirs(d, exist_ok=True)

    with open(os.path.join(prov_ds, "default.yml"), "w") as f:
        json.dump({
            "apiVersion": 1,
            "datasources": [{
                "name": "ray-tpu-prometheus",
                "type": "prometheus",
                "access": "proxy",
                "isDefault": True,
                "url": "http://127.0.0.1:9090",
            }],
        }, f, indent=2)
    with open(os.path.join(prov_db, "default.yml"), "w") as f:
        json.dump({
            "apiVersion": 1,
            "providers": [{
                "name": "ray-tpu",
                "folder": "",
                "type": "file",
                "options": {"path": dash_dir},
            }],
        }, f, indent=2)
    with open(os.path.join(dash_dir, "default_grafana_dashboard.json"), "w") as f:
        json.dump(default_dashboard(), f, indent=2)
    with open(os.path.join(graf_dir, "grafana.ini"), "w") as f:
        f.write("[paths]\nprovisioning = {}\n[server]\nhttp_port = 3000\n".format(
            os.path.join(graf_dir, "provisioning")))
    return root
