"""Cluster-wide internal KV (reference python/ray/experimental/internal_kv.py
over GcsInternalKVManager, gcs_kv_manager.h:104). Driver talks to the in-process
GCS table directly; workers go through their control pipe."""
from __future__ import annotations

from typing import List, Optional

from ray_tpu.core import global_state


def _kv(op: str, *args):
    cluster = global_state.try_cluster()
    if cluster is not None:
        return getattr(cluster.gcs.kv, op)(*args)
    w = global_state.worker()
    return w.kv_request(op, *args)


def _internal_kv_initialized() -> bool:
    return global_state.try_worker() is not None


def _internal_kv_put(key: bytes, value: bytes, overwrite: bool = True,
                     namespace: str = "") -> bool:
    return _kv("put", key, value, namespace, overwrite)


def _internal_kv_get(key: bytes, namespace: str = "") -> Optional[bytes]:
    return _kv("get", key, namespace)


def _internal_kv_del(key: bytes, namespace: str = "") -> bool:
    return _kv("delete", key, namespace)


def _internal_kv_exists(key: bytes, namespace: str = "") -> bool:
    return _kv("exists", key, namespace)


def _internal_kv_list(prefix: bytes, namespace: str = "") -> List[bytes]:
    return _kv("keys", prefix, namespace)
