"""Experimental features (reference python/ray/experimental/)."""
