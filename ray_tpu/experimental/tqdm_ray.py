"""Distributed-safe progress bars.

Capability parity: reference python/ray/experimental/tqdm_ray.py — tqdm-shaped
bars whose updates from worker processes relay to the driver (instead of each
process fighting over the terminal). Worker-side bars push state through the
metrics channel; the driver renders one line per bar on stderr.
"""
from __future__ import annotations

import sys
import threading
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu.config import memoized_flag

# read on every update() — memoized against the raw env string
_render_min_interval = memoized_flag("tqdm_render_interval_s")


class tqdm:  # noqa: N801 - reference exports the lowercase name
    def __init__(self, iterable=None, desc: str = "", total: Optional[int] = None,
                 position: int = 0, **_compat):
        self._iterable = iterable
        self.desc = desc
        self.total = total if total is not None else (
            len(iterable) if hasattr(iterable, "__len__") else None)
        self.n = 0
        self._uuid = uuid.uuid4().hex
        self._last_render = 0.0
        self._closed = False

    # -- tqdm API ---------------------------------------------------------------
    def update(self, n: int = 1) -> None:
        self.n += n
        self._emit()

    def set_description(self, desc: str) -> None:
        self.desc = desc
        self._emit()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._emit(force=True)

    def __iter__(self):
        for x in self._iterable:
            yield x
            self.update(1)
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- relay ------------------------------------------------------------------
    def _state(self) -> Dict[str, Any]:
        return {"uuid": self._uuid, "desc": self.desc, "n": self.n,
                "total": self.total, "closed": self._closed}

    def _emit(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_render < _render_min_interval():
            return
        self._last_render = now
        from ray_tpu.core import global_state

        w = global_state.try_worker()
        if w is not None and hasattr(w, "push_tqdm"):
            try:  # worker: relay to the driver over its one-way channel
                w.push_tqdm(self._state())
                return
            # graftlint: allow[swallowed-exception] progress-bar forwarding is cosmetic; the worker must not die for it
            except Exception:
                pass
        _render_local(self._state())


_render_lock = threading.Lock()
_last_rendered_uuid: list = [None]


def ensure_newline() -> None:
    """Finalize an in-progress bar line before other stderr output.

    Bars re-render with a trailing "\\r", so the cursor normally sits ON the
    bar line between updates; a logger/warning writing to stderr at that
    moment (e.g. the telemetry ring-overflow warning) would splice into the
    bar. Call this first: if a bar line is pending, it is closed with a
    newline and the next bar update redraws on a fresh line."""
    with _render_lock:
        if _last_rendered_uuid[0] is not None:
            sys.stderr.write("\n")
            sys.stderr.flush()
            _last_rendered_uuid[0] = None


def _render_local(state: Dict[str, Any]) -> None:
    """Driver-side render. Concurrent bars interleave: when a different bar than
    the previous one renders, the old line is finalized with a newline first so
    bars never clobber each other mid-line."""
    with _render_lock:
        n, total = state["n"], state["total"]
        frac = f"{n}/{total}" if total else str(n)
        bar = ""
        if total:
            filled = int(20 * min(1.0, n / max(total, 1)))
            bar = "[" + "#" * filled + "-" * (20 - filled) + "] "
        if (_last_rendered_uuid[0] is not None
                and _last_rendered_uuid[0] != state["uuid"]):
            sys.stderr.write("\n")
        end = "\n" if state.get("closed") else "\r"
        _last_rendered_uuid[0] = None if state.get("closed") else state["uuid"]
        sys.stderr.write(f"{state['desc']}: {bar}{frac}{end}")
        sys.stderr.flush()
