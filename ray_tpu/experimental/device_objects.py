"""Device-resident object fast path.

Capability parity: reference python/ray/experimental/gpu_object_manager/
(GPUObjectManager gpu_object_manager.py:54 — tensors stay on device, refs travel
through plasma, NCCL transfer on demand). TPU shape of the idea, three tiers:

1. Same-process resolve returns the ORIGINAL array via a weak registry — zero
   copies, zero device↔host traffic.
2. Cross-process consumers pull device-to-device over the transfer plane
   (core/device_plane.py: PJRT transfer server, DCN on pods) when
   ``RAY_TPU_DEVICE_OBJECTS`` is "fetch" (default) or "native"; the producer
   export is pinned until the object is freed cluster-wide.
3. Fallback is the serialized host copy (device_put on deserialize) — always
   present in "fetch" mode, absent in "native" mode where only a stub is stored
   (the true GPU-objects analogue: producer death surfaces ObjectLostError and
   lineage reconstruction re-runs the producing task).

Weak references mean the same-process fast path never extends object lifetime;
the plane export (tier 2) does — it is released when the object is freed.
"""
from __future__ import annotations

import weakref
from typing import Any, Dict, Optional

_registry: "weakref.WeakValueDictionary[bytes, Any]" = weakref.WeakValueDictionary()
_exports: Dict[bytes, bytes] = {}  # oid bytes -> device-plane export key


def is_device_array(obj: Any) -> bool:
    """True for jax.Array values (checked without importing jax eagerly)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return isinstance(obj, jax.Array)
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
    except Exception:
        return False


def stash(oid_bytes: bytes, obj: Any) -> None:
    try:
        _registry[oid_bytes] = obj
    except TypeError:
        pass  # not weakref-able


def lookup(oid_bytes: Optional[bytes]) -> Optional[Any]:
    if oid_bytes is None:
        return None
    hit = _registry.get(oid_bytes)
    if hit is None:
        return None
    # a donated/deleted array (jit donate_argnums) keeps its Python shell alive;
    # fall back to the durable serialized copy instead of handing out dead buffers
    try:
        if hit.is_deleted():
            return None
    # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
    except Exception:
        pass
    return hit


def drop(oid_bytes: bytes) -> None:
    _registry.pop(oid_bytes, None)
    key = _exports.pop(oid_bytes, None)
    if key is not None:
        from ray_tpu.core import device_plane

        device_plane.plane().release(key)


# ------------------------------------------------------- cross-process device path

def wrap_for_store(oid_bytes: bytes, obj: Any) -> Any:
    """Called by object_store.materialize: swap a big jax.Array for a form whose
    deserialization pulls device-to-device instead of rehydrating host bytes.

    "fetch" mode keeps the host copy inside the wrapper (durability unchanged,
    consumers merely PREFER the device pull); "native" stores only a stub."""
    from ray_tpu.config import CONFIG

    mode = (CONFIG.device_objects or "off").lower()
    if mode not in ("fetch", "native") or not is_device_array(obj):
        return obj
    if obj.nbytes < CONFIG.device_object_min_bytes:
        return obj
    from ray_tpu.core import device_plane

    dp = device_plane.plane()
    if not dp.available:
        return obj
    try:
        handle = dp.export(obj)
    except device_plane.DevicePlaneError:
        return obj
    _exports[oid_bytes] = handle.key
    if mode == "native":
        return _DeviceNative(handle)
    return _DeviceBacked(handle, obj)


class _DeviceBacked:
    """Serialized form = (handle, host copy). Deserializers try the device pull
    first and fall back to device_put of the host bytes."""

    def __init__(self, handle, arr):
        self.handle = handle
        self.arr = arr

    def __reduce__(self):
        import numpy as np

        return (_rebuild_fetch, (self.handle, np.asarray(self.arr)))


class _DeviceNative:
    """Serialized form = handle only (no host bytes). Producer death surfaces
    ObjectLostError so lineage reconstruction can re-run the producing task."""

    def __init__(self, handle):
        self.handle = handle

    def __reduce__(self):
        return (_rebuild_native, (self.handle,))


def _rebuild_fetch(handle, host_np):
    from ray_tpu.core import device_plane

    try:
        return device_plane.plane().fetch(handle)
    # graftlint: allow[swallowed-exception] device-put fallback: handler re-puts the host copy instead
    except Exception:
        import jax

        return jax.device_put(host_np)


def _rebuild_native(handle):
    from ray_tpu.core import device_plane

    try:
        return device_plane.plane().fetch(handle)
    except Exception as e:
        from ray_tpu.core.exceptions import ObjectLostError

        raise ObjectLostError(
            f"device-native object unavailable ({e}); producer gone — "
            "reconstruction required") from e
