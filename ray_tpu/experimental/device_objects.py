"""Device-resident object fast path.

Capability parity: reference python/ray/experimental/gpu_object_manager/
(GPUObjectManager gpu_object_manager.py:54 — tensors stay on device, refs travel
through plasma, NCCL transfer on demand). TPU shape of the idea: a jax.Array put
into the object store keeps its device buffers alive in the producing process
(weak registry), so a same-process resolve returns the ORIGINAL array — zero
copies, zero device↔host traffic. Cross-process consumers fall back to the
serialized host copy (device_put on deserialize); cross-host transfer rides DCN
the same way. Weak references mean the fast path never extends object lifetime:
if the producer drops the array, consumers transparently use the durable copy.
"""
from __future__ import annotations

import weakref
from typing import Any, Optional

_registry: "weakref.WeakValueDictionary[bytes, Any]" = weakref.WeakValueDictionary()


def is_device_array(obj: Any) -> bool:
    """True for jax.Array values (checked without importing jax eagerly)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return isinstance(obj, jax.Array)
    except Exception:
        return False


def stash(oid_bytes: bytes, obj: Any) -> None:
    try:
        _registry[oid_bytes] = obj
    except TypeError:
        pass  # not weakref-able


def lookup(oid_bytes: Optional[bytes]) -> Optional[Any]:
    if oid_bytes is None:
        return None
    hit = _registry.get(oid_bytes)
    if hit is None:
        return None
    # a donated/deleted array (jit donate_argnums) keeps its Python shell alive;
    # fall back to the durable serialized copy instead of handing out dead buffers
    try:
        if hit.is_deleted():
            return None
    except Exception:
        pass
    return hit


def drop(oid_bytes: bytes) -> None:
    _registry.pop(oid_bytes, None)
