"""Usage stats: local-only feature-usage recording, off by default.

Capability parity: reference python/ray/_private/usage/ (opt-out usage stats
ping). This build NEVER phones home — there is no egress in the target
environment and none is wanted; instead, when enabled via RAY_TPU_USAGE_STATS=1
a feature-usage summary accumulates in the session dir for operators to inspect
(`ray_tpu.usage.usage_report()`).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import Counter
from typing import Dict

_lock = threading.Lock()
_features: Counter = Counter()


def usage_stats_enabled() -> bool:
    from ray_tpu.config import CONFIG

    return CONFIG.usage_stats


def record_library_usage(feature: str) -> None:
    """Called by subsystem entry points: serve.run, Dataset reads, Trainer.fit,
    Tuner.fit, Algorithm.setup, JaxLLMEngine.start."""
    if not usage_stats_enabled():
        return
    with _lock:
        _features[feature] += 1


def usage_report() -> Dict[str, int]:
    with _lock:
        return dict(_features)


def reset() -> None:
    """Clear recorded usage (tests, session boundaries)."""
    with _lock:
        _features.clear()


def flush_to_session_dir() -> str:
    from ray_tpu.job.manager import default_session_dir

    path = os.path.join(default_session_dir(), "usage_stats.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"recorded_at": time.time(), "features": usage_report()}, f)
    return path
