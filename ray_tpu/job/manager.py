"""JobManager: run driver scripts as supervised subprocesses.

Capability parity: reference python/ray/dashboard/modules/job/ — `ray job submit`
runs the entrypoint under a supervisor actor, tracks status (PENDING/RUNNING/
SUCCEEDED/FAILED/STOPPED), captures logs, applies the job's runtime_env
(job_manager.py, job_supervisor). Here the supervisor is a driver-side thread
per job and state persists in a session directory so the CLI can inspect it.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@dataclasses.dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str
    start_time: float
    end_time: Optional[float] = None
    return_code: Optional[int] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self):
        return dataclasses.asdict(self)


def default_session_dir() -> str:
    from ray_tpu.config import CONFIG

    return CONFIG.session_dir


class JobManager:
    def __init__(self, session_dir: Optional[str] = None):
        self.session_dir = session_dir or default_session_dir()
        self.jobs_dir = os.path.join(self.session_dir, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    # -- persistence ------------------------------------------------------------
    def _job_dir(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id)

    def _save(self, info: JobInfo) -> None:
        path = os.path.join(self._job_dir(info.job_id), "info.json")
        with open(path + ".tmp", "w") as f:
            json.dump(info.to_dict(), f)
        os.replace(path + ".tmp", path)

    def _load(self, job_id: str) -> Optional[JobInfo]:
        try:
            with open(os.path.join(self._job_dir(job_id), "info.json")) as f:
                return JobInfo(**json.load(f))
        except (OSError, json.JSONDecodeError):
            return None

    # -- API --------------------------------------------------------------------
    def submit_job(self, entrypoint: str, *,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        jd = self._job_dir(job_id)
        if os.path.exists(jd):
            raise ValueError(f"job {job_id} already exists")
        os.makedirs(jd)
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       status=JobStatus.PENDING, start_time=time.time(),
                       metadata=metadata or {})
        self._save(info)

        env = dict(os.environ)
        renv = runtime_env or {}
        env.update(renv.get("env_vars") or {})
        if renv.get("py_modules"):
            extra = os.pathsep.join(renv["py_modules"])
            env["PYTHONPATH"] = extra + os.pathsep + env.get("PYTHONPATH", "")
        cwd = renv.get("working_dir") or os.getcwd()
        log_path = os.path.join(jd, "driver.log")

        log_f = open(log_path, "wb")
        proc = subprocess.Popen(entrypoint, shell=True, cwd=cwd, env=env,
                                stdout=log_f, stderr=subprocess.STDOUT,
                                start_new_session=True)
        log_f.close()
        with self._lock:
            self._procs[job_id] = proc
        info.status = JobStatus.RUNNING
        self._save(info)

        def supervise():
            rc = proc.wait()
            cur = self._load(job_id)
            if cur is None or cur.status == JobStatus.STOPPED:
                return
            cur.status = JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
            cur.return_code = rc
            cur.end_time = time.time()
            self._save(cur)
            with self._lock:
                self._procs.pop(job_id, None)

        threading.Thread(target=supervise, daemon=True,
                         name=f"job-supervisor-{job_id}").start()
        return job_id

    def get_job_status(self, job_id: str) -> str:
        info = self._load(job_id)
        if info is None:
            raise KeyError(f"unknown job {job_id}")
        return info.status

    def get_job_info(self, job_id: str) -> JobInfo:
        info = self._load(job_id)
        if info is None:
            raise KeyError(f"unknown job {job_id}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        try:
            with open(os.path.join(self._job_dir(job_id), "driver.log")) as f:
                return f.read()
        except OSError:
            return ""

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for jid in sorted(os.listdir(self.jobs_dir)):
            info = self._load(jid)
            if info is not None:
                out.append(info)
        return out

    def stop_job(self, job_id: str) -> bool:
        info = self._load(job_id)
        if info is None:
            raise KeyError(f"unknown job {job_id}")
        with self._lock:
            proc = self._procs.get(job_id)
        if proc is None or proc.poll() is not None:
            return False
        # SIGTERM the whole process group (shell + script)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            from ray_tpu.config import CONFIG

            proc.wait(timeout=CONFIG.job_stop_grace_s)
        except subprocess.TimeoutExpired:
            with __import__("contextlib").suppress(ProcessLookupError):
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait()
        info.status = JobStatus.STOPPED
        info.end_time = time.time()
        info.return_code = proc.returncode
        self._save(info)
        return True

    def wait_job(self, job_id: str, timeout: Optional[float] = None) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"job {job_id} still {status}")
            time.sleep(0.2)
