"""JobSubmissionClient — the reference SDK surface (python/ray/dashboard/modules/
job/sdk.py:36, submit_job :126) over the local JobManager. The reference client
speaks HTTP to the dashboard; here jobs are tracked in the shared session dir, so
a client in any process sees the same jobs as the CLI."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .manager import JobInfo, JobManager


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None,
                 session_dir: Optional[str] = None):
        # address kept for API compatibility; the local manager needs none
        self._mgr = JobManager(session_dir)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None) -> str:
        return self._mgr.submit_job(entrypoint, runtime_env=runtime_env,
                                    metadata=metadata, submission_id=submission_id)

    def get_job_status(self, job_id: str) -> str:
        return self._mgr.get_job_status(job_id)

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._mgr.get_job_info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._mgr.get_job_logs(job_id)

    def list_jobs(self) -> List[JobInfo]:
        return self._mgr.list_jobs()

    def stop_job(self, job_id: str) -> bool:
        return self._mgr.stop_job(job_id)

    def wait_job(self, job_id: str, timeout: Optional[float] = None) -> str:
        return self._mgr.wait_job(job_id, timeout)
