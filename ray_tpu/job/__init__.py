"""Job submission (reference python/ray/dashboard/modules/job/ + JobSubmissionClient)."""
from .manager import JobInfo, JobManager, JobStatus
from .client import JobSubmissionClient

__all__ = ["JobManager", "JobInfo", "JobStatus", "JobSubmissionClient"]
