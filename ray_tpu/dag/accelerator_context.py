"""Pluggable communicator registry for compiled-DAG channels.

Capability parity: reference python/ray/experimental/channel/accelerator_context.py
(:18 AcceleratorContext, :221 register_accelerator_context) + communicator.py:18
(Communicator ABC) — the reference's own extension point for mapping a device
type to the transport its compiled graphs use (NCCL for CUDA there). Here the
registered transports are:
- "cpu"/"shm": the seqlock shared-memory channel (default)
- "tpu"/"device": jax.Array-aware channel — a same-process reader receives THE
  original device array (zero-copy via experimental.device_objects); across
  processes the host copy embedded in the message is used. True device-to-device
  between jitted stages should be fused into one pjit program or ride
  jax.device_put, per the dag module docstring.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Type

from .channel import ShmChannel


class Communicator:
    """Creates channels for compiled-DAG edges (reference communicator.py:18)."""

    def create_channel(self, name: str, capacity: int, create: bool = False):
        raise NotImplementedError


class SharedMemoryCommunicator(Communicator):
    def create_channel(self, name: str, capacity: int, create: bool = False):
        return ShmChannel(name, capacity, create=create)


class DeviceChannel:
    """ShmChannel wrapper that keeps device arrays resident for local readers."""

    def __init__(self, name: str, capacity: int, create: bool = False):
        self._inner = ShmChannel(name, capacity, create=create)

    @property
    def name(self) -> str:
        return self._inner.name

    @property
    def capacity(self) -> int:
        return self._inner.capacity

    @staticmethod
    def _device_payload(value: Any):
        """The device array inside a payload: bare, or one level deep in the
        (status, value) tuples compiled-DAG exec loops wrap everything in."""
        from ray_tpu.experimental import device_objects

        if device_objects.is_device_array(value):
            return value, "bare"
        if (isinstance(value, tuple) and len(value) == 2
                and device_objects.is_device_array(value[1])):
            return value[1], "pair"
        return None, None

    def write(self, value: Any, timeout: float = None) -> None:
        from ray_tpu.experimental import device_objects

        arr, shape = self._device_payload(value)
        if arr is not None:
            key = os.urandom(20)
            device_objects.stash(key, arr)  # same-process readers skip the copy
            self._inner.write(("__device__", key, shape, value), timeout)
        else:
            self._inner.write(("__host__", None, None, value), timeout)

    def read(self, timeout: float = None) -> Any:
        from ray_tpu.experimental import device_objects

        kind, key, shape, value = self._inner.read(timeout)
        if kind == "__device__":
            hit = device_objects.lookup(key)
            if hit is not None:  # zero-copy: splice THE original jax.Array back in
                return hit if shape == "bare" else (value[0], hit)
        return value

    def close(self) -> None:
        self._inner.close()

    def destroy(self) -> None:
        self._inner.destroy()

    def __reduce__(self):
        inner = self._inner.__reduce__()
        return (_rebuild_device_channel, inner[1])


def _rebuild_device_channel(*args):
    ch = DeviceChannel.__new__(DeviceChannel)
    ch._inner = ShmChannel(*args)
    return ch


class DeviceCommunicator(Communicator):
    def create_channel(self, name: str, capacity: int, create: bool = False):
        return DeviceChannel(name, capacity, create=create)


_registry: Dict[str, Type[Communicator]] = {
    "cpu": SharedMemoryCommunicator,
    "shm": SharedMemoryCommunicator,
    "tpu": DeviceCommunicator,
    "device": DeviceCommunicator,
}


def register_accelerator_context(device_type: str, communicator_cls: Type[Communicator]) -> None:
    """Reference accelerator_context.py:221 — plug a custom transport in."""
    if not issubclass(communicator_cls, Communicator):
        raise TypeError("communicator_cls must subclass Communicator")
    _registry[device_type] = communicator_cls


def get_accelerator_context(device_type: str = "cpu") -> Communicator:
    try:
        return _registry[device_type]()
    except KeyError:
        raise ValueError(
            f"no communicator registered for {device_type!r} "
            f"(known: {sorted(_registry)})") from None
