"""Pluggable communicator registry for compiled-DAG channels.

Capability parity: reference python/ray/experimental/channel/accelerator_context.py
(:18 AcceleratorContext, :221 register_accelerator_context) + communicator.py:18
(Communicator ABC) — the reference's own extension point for mapping a device
type to the transport its compiled graphs use (NCCL for CUDA there). Here the
registered transports are:
- "cpu"/"shm": the seqlock shared-memory channel (default)
- "tpu"/"device": jax.Array-aware channel — a same-process reader receives THE
  original device array (zero-copy via experimental.device_objects); a
  cross-process reader pulls it device-to-device over the transfer plane
  (core/device_plane.py — the NCCL-channel analogue, reference
  torch_tensor_nccl_channel.py), with the embedded host copy as fallback only
  when the plane is off. Fusing stages into one pjit program remains the fastest
  path when all stages are pure functions, per the dag module docstring.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Type

from .channel import ShmChannel


class Communicator:
    """Creates channels for compiled-DAG edges (reference communicator.py:18)."""

    def create_channel(self, name: str, capacity: int, create: bool = False):
        raise NotImplementedError


class SharedMemoryCommunicator(Communicator):
    def create_channel(self, name: str, capacity: int, create: bool = False):
        return ShmChannel(name, capacity, create=create)


class DeviceChannel:
    """ShmChannel wrapper that keeps device arrays resident: same-process readers
    splice the original array back in; cross-process readers pull device-to-device
    over the transfer plane (reader acks → writer export released; a small LRU cap
    bounds pinned HBM when the reader is same-process and never pulls)."""

    # Live exports kept per channel before the oldest is force-released. A reader
    # lagging within the channel's write capacity still pulls fine; beyond that
    # only same-process readers (who never pull) are affected.
    _EXPORT_CAP = 4

    def __init__(self, name: str, capacity: int, create: bool = False):
        self._inner = ShmChannel(name, capacity, create=create)
        self._live_exports: list = []

    @property
    def name(self) -> str:
        return self._inner.name

    @property
    def capacity(self) -> int:
        return self._inner.capacity

    @staticmethod
    def _device_payload(value: Any):
        """The device array inside a payload: bare, or one level deep in the
        (status, value) tuples compiled-DAG exec loops wrap everything in."""
        from ray_tpu.experimental import device_objects

        if device_objects.is_device_array(value):
            return value, "bare"
        if (isinstance(value, tuple) and len(value) == 2
                and device_objects.is_device_array(value[1])):
            return value[1], "pair"
        return None, None

    def write(self, value: Any, timeout: float = None) -> None:
        from ray_tpu.core import device_plane
        from ray_tpu.experimental import device_objects

        arr, shape = self._device_payload(value)
        if arr is None:
            self._inner.write(("__host__", None, None, value, None), timeout)
            return
        key = os.urandom(20)
        device_objects.stash(key, arr)  # same-process readers skip the copy
        handle = None
        dp = device_plane.plane()
        from ray_tpu.config import CONFIG

        # Small arrays keep the embedded host copy: the arm round-trip isn't
        # worth it, and the host frame lets ANY reader proceed. Big arrays go
        # device-native — both endpoints of a "device" channel must then have
        # the plane up (NCCL-channel semantics in the reference).
        if dp.available and arr.nbytes >= CONFIG.device_object_min_bytes:
            try:
                handle = dp.export(arr)
            except device_plane.DevicePlaneError:
                handle = None
        if handle is not None:
            # Device-native frame: NO host copy of the payload rides the shm
            # channel — a cross-process reader pulls the buffers directly.
            rest = value[0] if shape == "pair" else None
            self._live_exports.append(handle.key)
            while len(self._live_exports) > self._EXPORT_CAP:
                dp.release(self._live_exports.pop(0))
            self._inner.write(("__device__", key, shape, rest, handle), timeout)
        else:
            self._inner.write(("__device_host__", key, shape, value, None), timeout)

    def read(self, timeout: float = None) -> Any:
        from ray_tpu.experimental import device_objects

        kind, key, shape, rest, handle = self._inner.read(timeout)
        if kind == "__host__":
            return rest
        # "__device__": rest = status half of a pair (or None); payload via plane.
        # "__device_host__": rest = the FULL original value (host copy embedded).
        status = rest[0] if (kind == "__device_host__" and shape == "pair") else rest
        hit = device_objects.lookup(key)
        if hit is not None:  # zero-copy: splice THE original jax.Array back in
            return hit if shape == "bare" else (status, hit)
        if kind == "__device_host__":
            return rest  # host copy embedded in the frame (plane off / small)
        from ray_tpu.core import device_plane

        try:
            arr = device_plane.plane().fetch(handle, release=True)
        except device_plane.DevicePlaneError as e:
            raise device_plane.DevicePlaneError(
                "device channel frame lost: this reader cannot pull from the "
                "writer's transfer plane (both endpoints of a 'device' channel "
                f"need RAY_TPU_DEVICE_PLANE and a shared session authkey): {e}"
            ) from e
        return arr if shape == "bare" else (status, arr)

    def close(self) -> None:
        self._release_all()
        self._inner.close()

    def destroy(self) -> None:
        self._release_all()
        self._inner.destroy()

    def _release_all(self) -> None:
        from ray_tpu.core import device_plane

        dp = device_plane.plane()
        while self._live_exports:
            dp.release(self._live_exports.pop())

    def __reduce__(self):
        inner = self._inner.__reduce__()
        return (_rebuild_device_channel, inner[1])


def _rebuild_device_channel(*args):
    ch = DeviceChannel.__new__(DeviceChannel)
    ch._inner = ShmChannel(*args)
    ch._live_exports = []
    return ch


class DeviceCommunicator(Communicator):
    def create_channel(self, name: str, capacity: int, create: bool = False):
        return DeviceChannel(name, capacity, create=create)


_registry: Dict[str, Type[Communicator]] = {
    "cpu": SharedMemoryCommunicator,
    "shm": SharedMemoryCommunicator,
    "tpu": DeviceCommunicator,
    "device": DeviceCommunicator,
}


def register_accelerator_context(device_type: str, communicator_cls: Type[Communicator]) -> None:
    """Reference accelerator_context.py:221 — plug a custom transport in."""
    if not issubclass(communicator_cls, Communicator):
        raise TypeError("communicator_cls must subclass Communicator")
    _registry[device_type] = communicator_cls


def get_accelerator_context(device_type: str = "cpu") -> Communicator:
    try:
        return _registry[device_type]()
    except KeyError:
        raise ValueError(
            f"no communicator registered for {device_type!r} "
            f"(known: {sorted(_registry)})") from None


def resolve_stage_transport(requested: str = "auto") -> str:
    """Pick the inter-stage block transport for the MPMD pipeline runner
    (train/mpmd_pipeline.py): "device" rides core/device_plane export/fetch
    (the same plane DeviceChannel uses) when this process has it; "host" is
    the striped data-plane byte path; "auto" probes and falls back — so a
    CPU-only stage and a TPU stage resolve independently, and the publish
    side degrades to host bytes per-block when an export is rejected."""
    if requested not in ("auto", "host", "device"):
        raise ValueError(f"unknown stage transport {requested!r} (auto|host|device)")
    if requested == "host":
        return "host"
    try:
        from ray_tpu.core import device_plane

        available = bool(device_plane.plane().available)
    # graftlint: allow[swallowed-exception] transport probe: an unimportable/failed device plane means host path, not an error
    except Exception:
        available = False
    if requested == "device" and not available:
        raise RuntimeError("transport='device' but the device plane is "
                           "unavailable in this process")
    return "device" if available else "host"
