"""Compiled actor DAGs: µs-scale repeated dispatch without per-call task RPC.

Capability parity: reference python/ray/dag/compiled_dag_node.py:808
(``CompiledDAG``) — an actor-method DAG is compiled once into (a) a channel per
edge and (b) one persistent exec loop per participating actor (reference
``do_exec_tasks`` :191); ``execute()`` then just writes the input channel and
reads the output channel (driver ``_execute_until`` :2476).

TPU note: between JAX stages the fastest path for device data is (1) fuse the
stages into ONE jitted program so XLA moves activations over ICI itself — do
this whenever all stages are pure functions. Otherwise (2) the "device" channel
type moves jax.Arrays device-to-device over the transfer plane
(core/device_plane.py — the NCCL-channel analogue; DCN on pods), with
same-process readers getting the original array zero-copy. Compiled DAGs here
exist for the orchestration win: pipelines of stateful actors (prefill/decode
disaggregation, env-runner → learner) dispatched at shared-memory latency.
"""
from __future__ import annotations

import os
import threading
import uuid
from typing import Any, Dict, List, Optional

from .channel import ShmChannel



class DAGNode:
    def __init__(self):
        self._id = uuid.uuid4().hex

    def experimental_compile(self, *, buffer_size_bytes: Optional[int] = None,
                             submit_timeout: float = 30.0,
                             max_inflight_executions: int = 2,
                             channel_type: str = "shm") -> "CompiledDAG":
        """channel_type selects the registered Communicator ("shm" default;
        "device" keeps jax.Arrays resident for same-process readers — reference
        accelerator_context.py registry)."""
        if buffer_size_bytes is None:
            from ray_tpu.config import CONFIG

            buffer_size_bytes = CONFIG.dag_channel_buffer_bytes
        return CompiledDAG(self, buffer_size_bytes, submit_timeout,
                           max_inflight_executions, channel_type)


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference dag/input_node.py)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)


class InputAttributeNode(DAGNode):
    """input[key] / input.attr access (reference dag/input_node.py)."""

    def __init__(self, parent: InputNode, key: Any):
        super().__init__()
        self.parent = parent
        self.key = key


class ClassMethodNode(DAGNode):
    """One actor-method call in the graph (reference dag/class_node.py)."""

    def __init__(self, actor, method_name: str, args: tuple, kwargs: dict):
        super().__init__()
        self.actor = actor
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def upstream(self) -> List[DAGNode]:
        return [a for a in list(self.args) + list(self.kwargs.values())
                if isinstance(a, DAGNode)]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)


def bind(actor_method, *args, **kwargs) -> ClassMethodNode:
    """actor.method.bind(...) — builds a node instead of submitting a task."""
    return ClassMethodNode(actor_method._handle, actor_method._name, args, kwargs)


# ------------------------------------------------------------------ exec loop

def _actor_exec_loop(instance, tasks: List[Dict], stop_name: str,
                     communicator_cls=None):
    """Runs inside the actor (via __ray_call__): read inputs, call methods, write
    outputs, until the stop channel fires. tasks are in topological order.

    The communicator CLASS travels with this call (cloudpickled), so custom
    transports registered only in the driver still work in the worker."""
    from .accelerator_context import SharedMemoryCommunicator

    comm = (communicator_cls or SharedMemoryCommunicator)()
    stop = ShmChannel(stop_name, 256)
    chans: Dict[str, Any] = {}

    def ch(name_cap):
        name, cap = name_cap
        if name not in chans:
            chans[name] = comm.create_channel(name, cap)
        return chans[name]

    while True:
        for t in tasks:
            # Block on the first input; by protocol every input for one round is
            # written before the next round can start. Every channel payload is a
            # (status, value) pair so upstream errors propagate instead of computing.
            vals = {}
            stopped = False
            upstream_err = None
            for key, src in t["inputs"].items():
                c = ch(src)
                while True:
                    try:
                        status, v = c.read(timeout=0.2)
                        if status == "err":
                            upstream_err = v
                        else:
                            vals[key] = v
                        break
                    except TimeoutError:
                        try:
                            stop.read(timeout=0)
                            stopped = True
                            break
                        except TimeoutError:
                            continue
                if stopped:
                    break
            if stopped:
                return
            if upstream_err is not None:
                wrapped = ("err", upstream_err)
            else:
                args = [vals[("a", i)] if ("a", i) in vals else v
                        for i, v in enumerate(t["args"])]
                kwargs = {k: vals.get(("k", k), v) for k, v in t["kwargs"].items()}
                try:
                    out = getattr(instance, t["method"])(*args, **kwargs)
                    wrapped = ("ok", out)
                except Exception as e:  # noqa: BLE001 - surfaced at the output channel
                    wrapped = ("err", e)
            for dst in t["outputs"]:
                while True:  # backpressured write, interruptible by teardown
                    try:
                        ch(dst).write(wrapped, timeout=0.2)
                        break
                    except TimeoutError:
                        try:
                            stop.read(timeout=0)
                            return
                        except TimeoutError:
                            continue


class CompiledDAGRef:
    """Future for one execute() round (reference compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx

    def get(self, timeout: Optional[float] = None):
        return self._dag._get_result(self._idx, timeout)


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size: int, submit_timeout: float,
                 max_inflight_executions: int = 2, channel_type: str = "shm"):
        from .accelerator_context import get_accelerator_context

        self._buffer = buffer_size
        self._channel_type = channel_type
        self._comm = get_accelerator_context(channel_type)
        self._timeout = submit_timeout
        # Single-slot channels bound the safe pipeline depth (reference analog:
        # max_inflight_executions on compiled_dag_node.py; exceeding it raises
        # rather than deadlocking on channel backpressure).
        self._max_inflight = max_inflight_executions
        self._lock = threading.Lock()
        self._results: Dict[int, Any] = {}
        self._next_submit = 0
        self._next_read = 0
        self._torn_down = False

        outputs = root.outputs if isinstance(root, MultiOutputNode) else [root]
        self._n_outputs = len(outputs)
        self._single = not isinstance(root, MultiOutputNode)

        # topo-sort the ClassMethodNodes
        order: List[ClassMethodNode] = []
        seen = {}

        def visit(n: DAGNode):
            if n._id in seen:
                return
            seen[n._id] = True
            if isinstance(n, ClassMethodNode):
                for u in n.upstream():
                    visit(u)
                order.append(n)
            elif isinstance(n, InputAttributeNode):
                pass
            elif isinstance(n, MultiOutputNode):
                for u in n.outputs:
                    visit(u)

        for o in outputs:
            visit(o)
        if not order:
            raise ValueError("compiled DAG contains no actor method calls")

        prefix = f"rtdag_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._stop_name = f"{prefix}_stop"
        self._stop = ShmChannel(self._stop_name, 256, create=True)
        self._all_channels: List[ShmChannel] = [self._stop]

        def new_chan(tag):
            c = self._comm.create_channel(f"{prefix}_{tag}", self._buffer, create=True)
            self._all_channels.append(c)
            return c

        # input channels: one per (consumer-node, arg-position) that reads the input
        self._input_chans: List[tuple] = []  # (channel, key-extractor)
        node_out: Dict[str, List] = {n._id: [] for n in order}  # downstream channel specs
        per_actor: Dict[Any, List[Dict]] = {}
        chan_i = 0

        for n in order:
            task = {"method": n.method_name, "args": [], "kwargs": {}, "inputs": {},
                    "outputs": []}

            def wire(pos_key, v):
                nonlocal chan_i
                if isinstance(v, (InputNode, InputAttributeNode)):
                    c = new_chan(f"in{chan_i}")
                    chan_i += 1
                    key = v.key if isinstance(v, InputAttributeNode) else None
                    self._input_chans.append((c, key))
                    task["inputs"][pos_key] = (c.name, c.capacity)
                    return None
                if isinstance(v, ClassMethodNode):
                    c = new_chan(f"e{chan_i}")
                    chan_i += 1
                    node_out[v._id].append((c.name, c.capacity))
                    task["inputs"][pos_key] = (c.name, c.capacity)
                    return None
                return v  # constant
            task["args"] = [wire(("a", i), v) for i, v in enumerate(n.args)]
            task["kwargs"] = {k: wire(("k", k), v) for k, v in n.kwargs.items()}
            task["_node"] = n
            per_actor.setdefault(n.actor, []).append(task)

        # output channels for DAG outputs
        self._output_chans: List[ShmChannel] = []
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError("DAG outputs must be actor method nodes")
            c = new_chan(f"out{chan_i}")
            chan_i += 1
            node_out[o._id].append((c.name, c.capacity))
            self._output_chans.append(c)

        # attach intermediate output specs to tasks
        for tasks in per_actor.values():
            for t in tasks:
                t["outputs"] = node_out[t.pop("_node")._id]

        # launch one exec loop per actor (long-running actor task)
        self._loop_refs = []
        for actor, tasks in per_actor.items():
            self._loop_refs.append(
                actor.__ray_call__.remote(_actor_exec_loop, tasks, self._stop_name,
                                          type(self._comm))
            )

    # -- execution -----------------------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        with self._lock:
            if self._next_submit - self._next_read >= self._max_inflight:
                raise RuntimeError(
                    f"{self._next_submit - self._next_read} executions in flight; "
                    f"call .get() on earlier results or raise max_inflight_executions")
            idx = self._next_submit
            self._next_submit += 1
            value = args[0] if len(args) == 1 and not kwargs else (args, kwargs)
            for c, key in self._input_chans:
                if key is None:
                    c.write(("ok", value), timeout=self._timeout)
                elif isinstance(value, dict) or isinstance(key, int):
                    c.write(("ok", value[key]), timeout=self._timeout)
                else:
                    c.write(("ok", getattr(value, key)), timeout=self._timeout)
        return CompiledDAGRef(self, idx)

    def _get_result(self, idx: int, timeout: Optional[float]):
        with self._lock:
            while self._next_read <= idx:
                outs = []
                for c in self._output_chans:
                    status, v = c.read(timeout=timeout or self._timeout)
                    outs.append((status, v))
                for status, v in outs:
                    if status == "err":
                        self._results[self._next_read] = ("err", v)
                        break
                else:
                    vals = [v for _, v in outs]
                    self._results[self._next_read] = (
                        "ok", vals[0] if self._single else vals)
                self._next_read += 1
        status, v = self._results.pop(idx)
        if status == "err":
            raise v
        return v

    # -- lifecycle -------------------------------------------------------------------
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        self._stop.write(True)
        try:
            import ray_tpu

            ray_tpu.wait(self._loop_refs, num_returns=len(self._loop_refs), timeout=5.0)
        # graftlint: allow[swallowed-exception] teardown wait: loop actors may already be dead
        except Exception:
            pass
        for c in self._all_channels:
            c.destroy()

    def __del__(self):
        try:
            self.teardown()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
