"""ray_tpu.dag — Compiled Graphs (aDAG) over shared-memory channels.

Capability parity: reference python/ray/dag/ (CompiledDAG, InputNode,
MultiOutputNode, .bind/.experimental_compile API; SURVEY.md §2.3). See
compiled.py for the TPU stance on device-to-device channels.

Usage (reference API shape):
    with InputNode() as inp:
        x = a1.step.bind(inp)
        y = a2.step.bind(x)
    dag = y.experimental_compile()
    out = dag.execute(5).get()
    dag.teardown()
"""
from .compiled import (
    ClassMethodNode,
    CompiledDAG,
    CompiledDAGRef,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    bind,
)

__all__ = [
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
    "ClassMethodNode",
    "DAGNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "bind",
]
