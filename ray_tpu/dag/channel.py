"""Single-slot shared-memory channels for compiled actor DAGs.

Capability parity: reference python/ray/experimental/channel/ — the
``shared_memory_channel.py`` mutable-plasma-object transport that Compiled Graphs
use to skip per-call task RPC. Here a channel is one POSIX shm segment with a
seqlock header: the writer bumps a sequence (odd = writing, even = ready), the
reader spins until a new even sequence appears. Single writer, single reader;
fan-out edges get one channel per consumer.

The reference's NCCL channel (torch_tensor_nccl_channel.py) has no analogue
here by design: device tensors between jitted stages should ride ICI inside one
pjit program or via jax.device_put — see dag/compiled.py docstring.
"""
from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any

import cloudpickle

_HEADER = struct.Struct("<QQQ")  # seq, ack, payload_len


class ChannelFullError(ValueError):
    pass


class ShmChannel:
    """One-slot SPSC channel with backpressure over a named shm segment.

    The writer blocks until the reader has acked the previous value (reference:
    compiled-graph channels apply backpressure so pipelined executions cannot
    overwrite unread results)."""

    def __init__(self, name: str, capacity: int, create: bool = False):
        self.name = name
        self.capacity = capacity
        if create:
            self._seg = shared_memory.SharedMemory(name=name, create=True,
                                                   size=capacity + _HEADER.size)
            _HEADER.pack_into(self._seg.buf, 0, 0, 0, 0)
        else:
            self._seg = shared_memory.SharedMemory(name=name)
        self._last_read = 0

    # -- wire ------------------------------------------------------------------
    def write(self, value: Any, timeout: float = None) -> None:
        payload = cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > self.capacity:
            raise ChannelFullError(
                f"serialized value ({len(payload)} B) exceeds channel capacity "
                f"({self.capacity} B); pass a larger buffer_size to experimental_compile")
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:  # backpressure: previous value must be consumed
            seq, ack, _ = _HEADER.unpack_from(self._seg.buf, 0)
            if seq == 0 or ack == seq:
                break
            spins += 1
            time.sleep(0 if spins < 1000 else 0.0002)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} write timed out (no reader ack)")
        _HEADER.pack_into(self._seg.buf, 0, seq + 1, ack, len(payload))  # odd: writing
        self._seg.buf[_HEADER.size:_HEADER.size + len(payload)] = payload
        _HEADER.pack_into(self._seg.buf, 0, seq + 2, ack, len(payload))  # even: ready

    def read(self, timeout: float = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, ack, ln = _HEADER.unpack_from(self._seg.buf, 0)
            if seq % 2 == 0 and seq != self._last_read and seq != 0:
                payload = bytes(self._seg.buf[_HEADER.size:_HEADER.size + ln])
                self._last_read = seq
                value = pickle.loads(payload)
                # publish the ack so the writer may reuse the slot
                _HEADER.pack_into(self._seg.buf, 0, seq, seq, ln)
                return value
            spins += 1
            if spins < 1000:
                time.sleep(0)  # yield, stay hot
            else:
                time.sleep(0.0002)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} read timed out")

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        try:
            self._seg.close()
        except BufferError:
            pass

    def destroy(self) -> None:
        try:
            self._seg.close()
        except BufferError:
            pass
        try:
            seg = shared_memory.SharedMemory(name=self.name)
            seg.unlink()
            seg.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass

    def __reduce__(self):
        return (ShmChannel, (self.name, self.capacity, False))
