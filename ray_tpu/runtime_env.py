"""RuntimeEnv: per-task/actor execution environment.

Capability parity: reference python/ray/runtime_env/runtime_env.py:157 (RuntimeEnv)
+ _private/runtime_env/ plugins. Supported here: ``env_vars`` (applied around task
execution; kept for an actor's lifetime), ``py_modules`` (local paths prepended to
sys.path), ``working_dir`` (chdir for the duration). Cloud plugins (pip/conda/
container) are out of scope on a hermetic single image — validated and rejected
explicitly rather than silently ignored.
"""
from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "py_modules", "working_dir"}
_UNSUPPORTED = {"pip", "conda", "container", "uv", "image_uri"}


class RuntimeEnv(dict):
    """Validated runtime-env mapping (reference RuntimeEnv is also dict-like)."""

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 py_modules: Optional[List[str]] = None,
                 working_dir: Optional[str] = None, **kwargs):
        super().__init__()
        bad = set(kwargs) & _UNSUPPORTED
        if bad:
            raise ValueError(
                f"runtime_env fields {sorted(bad)} require package installation, "
                f"which is unavailable in this environment")
        unknown = set(kwargs) - _SUPPORTED
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if py_modules:
            self["py_modules"] = [str(p) for p in py_modules]
        if working_dir:
            self["working_dir"] = str(working_dir)
        self.update(kwargs)


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]], permanent: bool = False):
    """Apply env_vars/py_modules/working_dir; restore on exit unless permanent
    (actors keep their env for their lifetime, reference worker-per-env)."""
    if not runtime_env:
        yield
        return
    env_vars = runtime_env.get("env_vars") or {}
    py_modules = runtime_env.get("py_modules") or []
    working_dir = runtime_env.get("working_dir")

    saved_env = {k: os.environ.get(k) for k in env_vars}
    saved_cwd = os.getcwd() if working_dir else None
    added_paths = []
    try:
        os.environ.update(env_vars)
        for p in py_modules:
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        if working_dir:
            os.chdir(working_dir)
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            for p in added_paths:
                with contextlib.suppress(ValueError):
                    sys.path.remove(p)
            if saved_cwd is not None:
                os.chdir(saved_cwd)
