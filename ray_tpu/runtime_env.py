"""RuntimeEnv: per-task/actor execution environment.

Capability parity: reference python/ray/runtime_env/runtime_env.py:157 (RuntimeEnv)
+ _private/runtime_env/ plugins. Supported here: ``env_vars`` (applied around task
execution; kept for an actor's lifetime), ``py_modules`` (local paths prepended to
sys.path), ``working_dir`` (chdir for the duration), ``pip`` and ``uv``
(per-env package overlays, content-hash cached in the session dir — reference
_private/runtime_env/pip.py + uv.py + uri_cache.py; work offline with local
package paths / --find-links; ``uv`` requires the uv binary on PATH),
``container``/``image_uri`` (the worker runs INSIDE the named image via
docker/podman with the session dir mounted — core/container.py; reference
_private/runtime_env/image_uri.py). ``conda`` is validated and rejected
explicitly rather than silently ignored (no conda in this environment).
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "py_modules", "working_dir", "pip", "uv",
              "container", "image_uri"}
_UNSUPPORTED = {"conda"}


class RuntimeEnv(dict):
    """Validated runtime-env mapping (reference RuntimeEnv is also dict-like)."""

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 py_modules: Optional[List[str]] = None,
                 working_dir: Optional[str] = None,
                 pip: Optional[Any] = None,
                 uv: Optional[Any] = None,
                 container: Optional[Dict[str, Any]] = None,
                 image_uri: Optional[str] = None, **kwargs):
        super().__init__()
        bad = set(kwargs) & _UNSUPPORTED
        if bad:
            raise ValueError(
                f"runtime_env fields {sorted(bad)} require package-manager or image "
                f"infrastructure that is unavailable in this environment")
        unknown = set(kwargs) - _SUPPORTED
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if py_modules:
            self["py_modules"] = [str(p) for p in py_modules]
        if working_dir:
            self["working_dir"] = str(working_dir)
        if container or image_uri:
            from ray_tpu.core.container import normalize_container_spec

            normalize_container_spec(  # validate eagerly (raises ValueError)
                {"container": container, "image_uri": image_uri})
            if container:
                self["container"] = dict(container)
            if image_uri:
                self["image_uri"] = str(image_uri)
        for field, spec in (("pip", pip), ("uv", uv)):
            if not spec:
                continue
            # list of specs, or {"packages": [...], "no_index": bool, "find_links": [...]}
            if isinstance(spec, (list, tuple)):
                spec = {"packages": [str(p) for p in spec]}
            if not isinstance(spec, dict) or not spec.get("packages"):
                raise TypeError(
                    f'{field} must be a list of specs or {{"packages": [...], ...}}')
            self[field] = spec
        self.update(kwargs)


# ---- pip plugin: content-hashed venvs (reference pip.py + uri_cache.py) ----------

def _envs_root() -> str:
    from ray_tpu.job.manager import default_session_dir

    return os.path.join(default_session_dir(), "runtime_envs")


def ensure_pip_env(pip: Dict[str, Any], timeout_s: float = 300.0,
                   tool: str = "pip") -> str:
    """Install a pip/uv spec into a content-hashed --target dir; returns that dir.

    A --target overlay (not a full venv) layers the requested packages over the
    base environment: the running interpreter's setuptools/pip (or the uv
    binary, reference _private/runtime_env/uv.py) do the build, the
    overlay dir rides sys.path like py_modules, and the base image's jax/numpy
    stay untouched. Concurrent workers race through a lockdir; losers wait for
    the .ready marker (reference pip.py builds per-env virtualenvs + URI cache)."""
    if isinstance(pip, (list, tuple)):
        # Ray's list shorthand: plain runtime_env dicts reach here un-normalized
        pip = {"packages": [str(p) for p in pip]}
    key = hashlib.sha256(json.dumps({"tool": tool, **pip}, sort_keys=True)
                         .encode()).hexdigest()[:16]
    root = os.path.join(_envs_root(), f"{tool}_{key}")
    ready = os.path.join(root, ".ready")
    if os.path.exists(ready):
        return root
    os.makedirs(_envs_root(), exist_ok=True)
    # flock, not a lockdir: the kernel releases it when the holder dies (even
    # SIGKILL mid-install), so there are no stale locks and no reclaim races
    import fcntl

    fd = os.open(root + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"pip runtime_env {key} build timed out") from None
                time.sleep(0.25)
        if os.path.exists(ready):  # built while we waited
            return root
        if tool == "uv":
            import shutil as _shutil

            uv_bin = _shutil.which("uv")
            if uv_bin is None:
                raise RuntimeError(
                    'runtime_env {"uv": ...} requires the uv binary on PATH')
            # --no-build-isolation: sdist builds use this interpreter's
            # setuptools, so local-path installs work offline like pip's
            cmd = [uv_bin, "pip", "install", "--target", root,
                   "--python", sys.executable, "--no-build-isolation", "--quiet"]
        else:
            cmd = [sys.executable, "-m", "pip", "install", "--target", root,
                   "--no-build-isolation", "--disable-pip-version-check", "--quiet"]
        if pip.get("no_index"):
            cmd.append("--no-index")
        for fl in pip.get("find_links", []):
            cmd += ["--find-links", str(fl)]
        cmd += [str(p) for p in pip["packages"]]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip runtime_env install failed:\n{proc.stdout}\n{proc.stderr}")
        open(ready, "w").write(key)
        return root
    finally:
        os.close(fd)  # releases the flock if held


def merge_runtime_envs(base: Optional[Dict[str, Any]],
                       override: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Job-default + per-call merge (reference runtime_env override semantics:
    per-call fields win whole, except env_vars which dict-merge)."""
    if not base:
        return dict(override) if override else None
    if not override:
        return dict(base)
    out = dict(base)
    out.update({k: v for k, v in override.items() if k != "env_vars"})
    env_vars = {**(base.get("env_vars") or {}), **(override.get("env_vars") or {})}
    if env_vars:
        out["env_vars"] = env_vars
    return out


def resolved_runtime_env(per_call: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """per_call merged over the cluster's job-level default, if any. Workers
    (nested submissions) read the default from the env var the head plants in
    worker_env, so the job default survives driver -> worker -> task chains."""
    from ray_tpu.core import global_state

    c = global_state.try_cluster()
    default = getattr(c, "default_runtime_env", None) if c is not None else None
    if default is None and c is None:
        # client-mode driver: the default lives on the ClientContext object
        w = global_state.try_worker()
        default = getattr(w, "default_runtime_env", None)
    if default is None and c is None:
        raw = os.environ.get("RAY_TPU_DEFAULT_RUNTIME_ENV")
        if raw:
            with contextlib.suppress(ValueError):
                default = json.loads(raw)
    return merge_runtime_envs(default, per_call)


def prewarm(runtime_env: Optional[Dict[str, Any]]) -> None:
    """Build this host's pip/uv overlays ahead of the first task (reference:
    the per-node runtime-env agent materializing envs at job start)."""
    if not runtime_env:
        return
    for tool in ("pip", "uv"):
        spec = runtime_env.get(tool)
        if spec:
            ensure_pip_env(spec, tool=tool)


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]], permanent: bool = False):
    """Apply env_vars/py_modules/working_dir; restore on exit unless permanent
    (actors keep their env for their lifetime, reference worker-per-env)."""
    if not runtime_env:
        yield
        return
    env_vars = runtime_env.get("env_vars") or {}
    py_modules = list(runtime_env.get("py_modules") or [])
    working_dir = runtime_env.get("working_dir")
    if runtime_env.get("pip"):
        # venv site-packages rides the same sys.path mechanism as py_modules
        py_modules.insert(0, ensure_pip_env(runtime_env["pip"]))
    if runtime_env.get("uv"):
        py_modules.insert(0, ensure_pip_env(runtime_env["uv"], tool="uv"))

    saved_env = {k: os.environ.get(k) for k in env_vars}
    saved_cwd = os.getcwd() if working_dir else None
    added_paths = []
    try:
        os.environ.update(env_vars)
        for p in py_modules:
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        if working_dir:
            os.chdir(working_dir)
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            for p in added_paths:
                with contextlib.suppress(ValueError):
                    sys.path.remove(p)
            if saved_cwd is not None:
                os.chdir(saved_cwd)
