"""Logical plan: lazy operator DAG + rule-based optimizer.

Capability parity: reference python/ray/data/_internal/logical/ (operators, optimizers.py,
rules/operator_fusion). A Dataset holds a chain of LogicalOperators; on execution the plan
is optimized (map fusion) and lowered to physical operators (execution.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOperator:
    """One node in the logical DAG (single upstream chain; Union/Zip hold extra inputs)."""

    name = "Op"

    def __init__(self, input_op: Optional["LogicalOperator"] = None):
        self.input_op = input_op

    def chain(self) -> List["LogicalOperator"]:
        ops, op = [], self
        while op is not None:
            ops.append(op)
            op = op.input_op
        return list(reversed(ops))

    def __repr__(self):
        return self.name


class Read(LogicalOperator):
    """Leaf: produces blocks from a datasource's read tasks."""

    name = "Read"

    def __init__(self, datasource, parallelism: int = -1):
        super().__init__(None)
        self.datasource = datasource
        self.parallelism = parallelism


class InputData(LogicalOperator):
    """Leaf: pre-materialized blocks (from_items / from_numpy / materialized sets)."""

    name = "InputData"

    def __init__(self, blocks: List[Any], metadata: List[Any]):
        super().__init__(None)
        self.blocks = blocks  # list of ObjectRef[Block] or raw Blocks
        self.metadata = metadata


@dataclasses.dataclass
class MapSpec:
    """A batch transform: block -> block. Fusable with neighbors.

    kind: map_batches|map_rows|filter|flat_map|add_column|drop_columns|select_columns
    """

    kind: str
    fn: Any  # callable, or class for actor-pool compute
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    batch_size: Optional[int] = None
    batch_format: Optional[str] = "numpy"
    zero_copy_batch: bool = False


class AbstractMap(LogicalOperator):
    """Any row/batch transform, carrying compute strategy + resource requests."""

    name = "Map"

    def __init__(
        self,
        input_op,
        spec: MapSpec,
        compute: Optional[str] = None,  # None=tasks, "actors"=actor pool
        ray_remote_args: Optional[Dict[str, Any]] = None,
        concurrency: Optional[Any] = None,
    ):
        super().__init__(input_op)
        self.specs = [spec]
        self.compute = compute
        self.ray_remote_args = ray_remote_args or {}
        self.concurrency = concurrency
        self.name = {
            "map_batches": "MapBatches",
            "map_rows": "Map",
            "filter": "Filter",
            "flat_map": "FlatMap",
        }.get(spec.kind, "Map")

    def fused_with(self, other: "AbstractMap") -> "AbstractMap":
        out = AbstractMap(self.input_op, self.specs[0], self.compute, self.ray_remote_args, self.concurrency)
        out.specs = self.specs + other.specs
        out.name = f"{self.name}->{other.name}"
        # Downstream actor-pool compute wins (GPU/stateful UDF dominates placement).
        out.compute = other.compute or self.compute
        out.ray_remote_args = {**self.ray_remote_args, **other.ray_remote_args}
        out.concurrency = other.concurrency or self.concurrency
        return out


class Limit(LogicalOperator):
    name = "Limit"

    def __init__(self, input_op, limit: int):
        super().__init__(input_op)
        self.limit = limit


class Sort(LogicalOperator):
    name = "Sort"

    def __init__(self, input_op, key: str, descending: bool = False):
        super().__init__(input_op)
        self.key = key
        self.descending = descending


class RandomShuffle(LogicalOperator):
    name = "RandomShuffle"

    def __init__(self, input_op, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class Repartition(LogicalOperator):
    name = "Repartition"

    def __init__(self, input_op, num_blocks: int):
        super().__init__(input_op)
        self.num_blocks = num_blocks


class Aggregate(LogicalOperator):
    name = "Aggregate"

    def __init__(self, input_op, key: Optional[str], aggs: List[Any]):
        super().__init__(input_op)
        self.key = key
        self.aggs = aggs


class Join(LogicalOperator):
    """Hash join (reference _internal/execution/operators/join.py + hash_shuffle.py)."""

    def __init__(self, input_op, other: LogicalOperator, on: str, how: str = "inner",
                 num_partitions: Optional[int] = None):
        super().__init__(input_op)
        if how not in ("inner", "left_outer", "right_outer", "full_outer"):
            raise ValueError(f"unsupported join type {how!r}")
        self.other = other
        self.on = on
        self.how = how
        self.num_partitions = num_partitions
        self.name = f"Join({how})"


class Union(LogicalOperator):
    name = "Union"

    def __init__(self, input_op, others: List[LogicalOperator]):
        super().__init__(input_op)
        self.others = others


class Zip(LogicalOperator):
    name = "Zip"

    def __init__(self, input_op, other: LogicalOperator):
        super().__init__(input_op)
        self.other = other


class Write(LogicalOperator):
    name = "Write"

    def __init__(self, input_op, datasink):
        super().__init__(input_op)
        self.datasink = datasink


# ---- optimizer --------------------------------------------------------------


def _rebuild(chain: List[LogicalOperator]) -> LogicalOperator:
    prev = None
    for op in chain:
        op.input_op = prev if not isinstance(op, (Read, InputData)) else None
        prev = op
    return prev


def fuse_maps(plan: LogicalOperator) -> LogicalOperator:
    """OperatorFusion rule: merge adjacent AbstractMap ops into one physical stage.

    Mirrors reference _internal/logical/rules/operator_fusion.py — fusing avoids a full
    serialize->object store->deserialize round trip per stage.
    """
    chain = plan.chain()
    out: List[LogicalOperator] = []
    for op in chain:
        if (
            out
            and isinstance(op, AbstractMap)
            and isinstance(out[-1], AbstractMap)
            and _compatible(out[-1], op)
        ):
            out[-1] = out[-1].fused_with(op)
        else:
            out.append(op)
    return _rebuild(out)


def _compatible(a: AbstractMap, b: AbstractMap) -> bool:
    # Task-pool ops fuse freely; an actor-pool op can absorb upstream task ops but two
    # distinct actor-pool stages keep their own pools (distinct constructors).
    if a.compute == "actors" and b.compute == "actors":
        return False
    if a.compute == "actors" and b.compute is None:
        return True
    return True


def fuse_read_maps(plan: LogicalOperator) -> LogicalOperator:
    """Fuse task-pool map stages directly into read tasks (skips one store round trip)."""
    chain = plan.chain()
    if (
        len(chain) >= 2
        and isinstance(chain[0], Read)
        and isinstance(chain[1], AbstractMap)
        and chain[1].compute != "actors"
        and not getattr(chain[0], "_fused_specs", None)
    ):
        chain[0]._fused_specs = chain[1].specs
        chain[0].name = f"Read->{chain[1].name}"
        chain = [chain[0]] + chain[2:]
    return _rebuild(chain)


def optimize(plan: LogicalOperator) -> LogicalOperator:
    # Plan nodes are shared between Datasets derived from a common parent; rules mutate
    # (relink input_op, set _fused_specs), so optimize a shallow copy of the chain.
    import copy

    copies = [copy.copy(op) for op in plan.chain()]
    plan = _rebuild(copies)
    return fuse_read_maps(fuse_maps(plan))
