"""Dataset: the lazy distributed data API.

Capability parity: reference python/ray/data/dataset.py:160 — map_batches (:449),
iter_batches (:4664), materialize (:5626), plus filter/flat_map/sort/shuffle/groupby/
split/union/zip/write_* and schema/count/take introspection.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu

from . import logical as L
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import BlockAccessor, BlockMetadata
from .context import DataContext
from .datasource import CSVDatasink, Datasink, JSONDatasink, ParquetDatasink
from .execution import RefBundle, StreamingExecutor
from .iterator import DataIterator
from .stats import DatasetStats


class Dataset:
    def __init__(self, plan: L.LogicalOperator, ctx: Optional[DataContext] = None):
        self._plan = plan
        self._ctx = ctx or DataContext.get_current()
        self._materialized: Optional[List[RefBundle]] = None
        self._stats: Optional[DatasetStats] = None

    # -- plan builders --------------------------------------------------------
    def _with(self, op: L.LogicalOperator) -> "Dataset":
        return Dataset(op, self._ctx)

    def _input_op(self) -> L.LogicalOperator:
        # Chain from materialized blocks if available (so reuse skips recompute).
        if self._materialized is not None:
            return L.InputData([b for b, _ in self._materialized], [m for _, m in self._materialized])
        return self._plan

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[str] = None,
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        concurrency: Optional[Any] = None,
        **_compat,
    ) -> "Dataset":
        if isinstance(fn, type) and compute is None:
            compute = "actors"
        spec = L.MapSpec(
            kind="map_batches", fn=fn, fn_args=fn_args, fn_kwargs=fn_kwargs or {},
            fn_constructor_args=fn_constructor_args, fn_constructor_kwargs=fn_constructor_kwargs or {},
            batch_size=batch_size, batch_format=batch_format,
        )
        remote_args = {}
        if num_cpus is not None:
            remote_args["num_cpus"] = num_cpus
        if num_tpus:
            remote_args["num_tpus"] = num_tpus
        return self._with(L.AbstractMap(self._input_op(), spec, compute, remote_args, concurrency))

    def map(self, fn: Callable[[Dict], Dict], **kw) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="map_rows", fn=fn)))

    def flat_map(self, fn: Callable[[Dict], List[Dict]], **kw) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="flat_map", fn=fn)))

    def filter(self, fn: Callable[[Dict], bool], **kw) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="filter", fn=fn)))

    def add_column(self, name: str, fn: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="add_column", fn=fn, fn_args=(name,))))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="drop_columns", fn=None, fn_args=(cols,))))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="select_columns", fn=None, fn_args=(cols,))))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._with(L.AbstractMap(self._input_op(), L.MapSpec(kind="rename_columns", fn=None, fn_args=(mapping,))))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(self._input_op(), n))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(self._input_op(), key, descending))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomShuffle(self._input_op(), seed))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.Repartition(self._input_op(), num_blocks))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union(self._input_op(), [o._input_op() for o in others]))

    def join(self, other: "Dataset", on: str, how: str = "inner",
             *, num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on a key column (reference dataset.py join /
        operators/join.py): both sides hash-partition on `on`, partitions join
        in parallel tasks. how: inner | left_outer | right_outer | full_outer."""
        return self._with(L.Join(self._input_op(), other._input_op(), on, how, num_partitions))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(self._input_op(), other._input_op()))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # -- execution ------------------------------------------------------------
    def materialize(self) -> "Dataset":
        if self._materialized is None:
            ex = StreamingExecutor(self._ctx)
            self._materialized = ex.execute(self._plan)
            self._stats = ex.stats
        return self

    def _bundles(self) -> List[RefBundle]:
        self.materialize()
        return self._materialized

    def stats(self) -> str:
        self.materialize()
        return self._stats.summary() if self._stats else ""

    # -- consumption ----------------------------------------------------------
    def iterator(self) -> DataIterator:
        if self._materialized is not None:
            return DataIterator(self._materialized)
        # not yet materialized: stream — batches yield while upstream reads/maps
        # are still producing, and early stops (take/limit) halt upstream work
        ex = StreamingExecutor(self._ctx)
        self._stats = ex.stats
        return DataIterator(ex.execute_iter(self._plan))

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_rows()

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append({k: (v.item() if isinstance(v, np.generic) else v) for k, v in row.items()})
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return [
            {k: (v.item() if isinstance(v, np.generic) else v) for k, v in row.items()}
            for row in self.iter_rows()
        ]

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            # graftlint: allow[no-print] Dataset.show()'s contract IS printing
            print(row)

    def count(self) -> int:
        total = 0
        for b, m in self._bundles():
            total += m.num_rows if m.num_rows >= 0 else BlockAccessor.for_block(ray_tpu.get(b)).num_rows()
        return total

    def num_blocks(self) -> int:
        return len(self._bundles())

    def schema(self):
        for b, m in self._bundles():
            if m.schema is not None:
                return m.schema
            return BlockAccessor.for_block(ray_tpu.get(b)).schema()
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def size_bytes(self) -> int:
        return sum(max(m.size_bytes, 0) for _, m in self._bundles())

    # -- aggregation shortcuts -------------------------------------------------
    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        ds = self._with(L.Aggregate(self._input_op(), None, list(aggs)))
        rows = ds.take_all()
        return rows[0] if rows else {}

    def sum(self, on: str):
        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str):
        return self.aggregate(Std(on)).get(f"std({on})")

    # -- splitting ------------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        bundles = self._bundles()
        if equal:
            total = self.count()
            per = total // n
            # rows beyond n*per are dropped (reference split(equal=True) semantics)
            ex = StreamingExecutor(self._ctx)
            shards_bundles = ex._slice_to_layout(bundles, [per] * n)
            return [Dataset._from_bundles([sb]) for sb in shards_bundles]
        shards: List[List[RefBundle]] = [[] for _ in range(n)]
        for i, bundle in enumerate(bundles):
            shards[i % n].append(bundle)
        return [Dataset._from_bundles(s) for s in shards]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        bundles = self._bundles()
        total = self.count()
        bounds = [0] + list(indices) + [total]
        sizes = [max(0, bounds[i + 1] - bounds[i]) for i in range(len(bounds) - 1)]
        ex = StreamingExecutor(self._ctx)
        shards_bundles = ex._slice_to_layout(bundles, sizes)
        return [Dataset._from_bundles([sb]) for sb in shards_bundles]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        n = ds.count()
        n_test = int(n * test_size) if isinstance(test_size, float) else test_size
        parts = ds.split_at_indices([n - n_test])
        return parts[0], parts[1]

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        import os as _os
        import zlib as _zlib

        rng_seed = seed if seed is not None else int.from_bytes(_os.urandom(4), "little")

        def sample_fn(batch: Dict[str, np.ndarray], fraction=fraction, rng_seed=rng_seed):
            n = len(next(iter(batch.values()))) if batch else 0
            # Salt by batch content so each block draws an independent mask.
            salt = _zlib.crc32(next(iter(batch.values())).tobytes()[:1024]) if n else 0
            rng = np.random.default_rng((rng_seed, salt))
            mask = rng.random(n) < fraction
            return {k: v[mask] for k, v in batch.items()}

        return self.map_batches(sample_fn, batch_format="numpy")

    # -- writes ---------------------------------------------------------------
    def _write(self, sink: Datasink) -> List[str]:
        ds = self._with(L.Write(self._input_op(), sink))
        return [r["path"] for r in ds.take_all()]

    def write_parquet(self, path: str) -> List[str]:
        return self._write(ParquetDatasink(path))

    def write_csv(self, path: str) -> List[str]:
        return self._write(CSVDatasink(path))

    def write_json(self, path: str) -> List[str]:
        return self._write(JSONDatasink(path))

    def write_webdataset(self, path: str) -> List[str]:
        from .datasource import WebDatasetDatasink

        return self._write(WebDatasetDatasink(path))

    def write_tfrecords(self, path: str) -> List[str]:
        from .datasource import TFRecordDatasink

        return self._write(TFRecordDatasink(path))

    # -- conversion -----------------------------------------------------------
    def to_pandas(self):
        return BlockAccessor.concat([ray_tpu.get(b) for b, _ in self._bundles()]).to_pandas()

    def to_arrow(self):
        return BlockAccessor.concat([ray_tpu.get(b) for b, _ in self._bundles()])

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return BlockAccessor.for_block(self.to_arrow()).to_numpy()

    # -- internal constructors -------------------------------------------------
    @staticmethod
    def _from_blocks(blocks: List[Any]) -> "Dataset":
        refs = [ray_tpu.put(b) for b in blocks]
        metas = [BlockAccessor.for_block(b).get_metadata() for b in blocks]
        ds = Dataset(L.InputData(refs, metas))
        ds._materialized = list(zip(refs, metas))
        return ds

    @staticmethod
    def _from_bundles(bundles: List[RefBundle]) -> "Dataset":
        ds = Dataset(L.InputData([b for b, _ in bundles], [m for _, m in bundles]))
        ds._materialized = list(bundles)
        return ds

    def __repr__(self):
        try:
            cols = self.columns() if self._materialized is not None else None
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (cols = None) by design
        except Exception:
            cols = None
        if cols is not None:
            return f"Dataset(num_blocks={len(self._materialized)}, columns={cols})"
        return f"Dataset(plan={'->'.join(str(o) for o in self._plan.chain())})"


class GroupedData:
    """Reference python/ray/data/grouped_data.py."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._with(L.Aggregate(self._ds._input_op(), self._key, list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key

        def apply(batch: Dict[str, np.ndarray]):
            keys = batch[key]
            out = []
            for k in sorted(set(keys.tolist())):
                mask = keys == k
                group = {c: v[mask] for c, v in batch.items()}
                out.append(BlockAccessor.batch_to_block(fn(group)))
            return BlockAccessor.concat(out)

        # groups must be colocated: sort by key first, single output block per input
        return self._ds.sort(key).repartition(1).map_batches(apply, batch_format="numpy")
