"""ray_tpu.data: lazy distributed datasets over the ray_tpu object store.

Capability parity: reference python/ray/data/ (read_api.py, dataset.py). Blocks are
arrow tables; the streaming executor schedules map stages as ray_tpu tasks/actor pools
with bounded in-flight work; `iter_jax_batches` hands sharded device arrays to trainers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import aggregate  # noqa: F401
from .aggregate import AggregateFn, Count, Max, Mean, Min, Quantile, Std, Sum  # noqa: F401
from .block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from .context import DataContext  # noqa: F401
from .dataset import Dataset, GroupedData  # noqa: F401
from .datasource import (  # noqa: F401
    BinaryDatasource,
    CSVDatasource,
    Datasink,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
    BigQueryDatasource,
    DeltaSharingDatasource,
    IcebergDatasource,
    LanceDatasource,
    MongoDatasource,
    SQLDatasource,
    TFRecordDatasource,
    WebDatasetDatasource,
)
from .iterator import DataIterator  # noqa: F401
from .logical import Read


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    from ray_tpu.usage import record_library_usage

    record_library_usage("data")
    return Dataset(Read(ds, parallelism))


def range(n: int, *, parallelism: int = -1, column: str = "id") -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n, column), parallelism)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return _read(ItemsDatasource(list(items)), parallelism)


def from_numpy(arrays, *, parallelism: int = -1) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return _read(NumpyDatasource(arrays), parallelism)


def from_pandas(df, *, parallelism: int = -1) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return from_arrow(table)


def from_arrow(table) -> Dataset:
    return Dataset._from_blocks([table])


def read_parquet(paths, *, columns: Optional[List[str]] = None, parallelism: int = -1, **kw) -> Dataset:
    return _read(ParquetDatasource(paths, columns=columns, **kw), parallelism)


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _read(CSVDatasource(paths, **kw), parallelism)


def read_json(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _read(JSONDatasource(paths, **kw), parallelism)


def read_binary_files(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _read(BinaryDatasource(paths, **kw), parallelism)


def read_images(paths, *, size=None, mode: str = "RGB",
                parallelism: int = -1) -> Dataset:
    """Image files -> {image, path, height, width} rows (reference
    read_images / image_datasource.py); size=(h, w) resizes on read."""
    return _read(ImageDatasource(paths, size=size, mode=mode), parallelism)


def read_text(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _read(TextDatasource(paths, **kw), parallelism)


def read_webdataset(paths, *, decode: bool = True, parallelism: int = -1) -> Dataset:
    """Tar shards of key-grouped samples (reference read_webdataset /
    webdataset_datasource.py): {"__key__", <ext>: decoded member, ...} rows."""
    return _read(WebDatasetDatasource(paths, decode=decode), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    """TFRecord files of tf.train.Example protos -> one column per feature
    (reference read_tfrecords; needs tensorflow)."""
    return _read(TFRecordDatasource(paths), parallelism)


def read_lance(uri: str, *, columns: Optional[List[str]] = None,
               parallelism: int = -1) -> Dataset:
    """Lance table (reference read_lance; needs the optional 'lance' package)."""
    return _read(LanceDatasource(uri, columns=columns), parallelism)


def read_bigquery(project_id: str, *, dataset: Optional[str] = None,
                  query: Optional[str] = None, parallelism: int = -1) -> Dataset:
    """BigQuery table or query (reference read_bigquery; needs
    'google-cloud-bigquery')."""
    return _read(BigQueryDatasource(project_id, dataset=dataset, query=query),
                 parallelism)


def read_sql(sql: str, connection_factory, *, parallelism: int = -1) -> Dataset:
    """Any DBAPI-2 database via a zero-arg connection factory (reference
    read_sql / _internal/datasource/sql_datasource.py — sqlite3, psycopg2,
    mysql-connector, ... all satisfy the protocol)."""
    return _read(SQLDatasource(sql, connection_factory), parallelism)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[dict]] = None,
               parallelism: int = -1) -> Dataset:
    """MongoDB collection (reference read_mongo; needs the optional
    'pymongo' package)."""
    return _read(MongoDatasource(uri, database, collection, pipeline=pipeline),
                 parallelism)


def read_iceberg(table_identifier: str, *, catalog_kwargs: Optional[dict] = None,
                 row_filter=None, selected_fields: Optional[List[str]] = None,
                 parallelism: int = -1) -> Dataset:
    """Iceberg table scan (reference read_iceberg; needs the optional
    'pyiceberg' package)."""
    return _read(IcebergDatasource(table_identifier,
                                   catalog_kwargs=catalog_kwargs,
                                   row_filter=row_filter,
                                   selected_fields=selected_fields), parallelism)


def read_delta_sharing_tables(url: str, *, limit: Optional[int] = None,
                              parallelism: int = -1) -> Dataset:
    """Delta Sharing table (reference read_delta_sharing_tables; needs the
    optional 'delta-sharing' package)."""
    return _read(DeltaSharingDatasource(url, limit=limit), parallelism)


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return _read(ds, parallelism)


__all__ = [
    "Dataset",
    "GroupedData",
    "DataIterator",
    "DataContext",
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "Datasource",
    "Datasink",
    "range",
    "from_items",
    "from_numpy",
    "from_pandas",
    "from_arrow",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_binary_files",
    "read_text",
    "read_webdataset",
    "read_tfrecords",
    "read_lance",
    "read_bigquery",
    "read_sql",
    "read_mongo",
    "read_iceberg",
    "read_delta_sharing_tables",
    "read_datasource",
    "AggregateFn",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
    "Quantile",
]
