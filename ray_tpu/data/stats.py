"""Per-operator execution stats (reference python/ray/data/_internal/stats.py)."""
from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass
class OpStats:
    name: str
    wall_s: float
    num_outputs: int
    output_rows: int


@dataclasses.dataclass
class DatasetStats:
    ops: List[OpStats] = dataclasses.field(default_factory=list)

    def summary(self) -> str:
        lines = ["Operator stats:"]
        for op in self.ops:
            lines.append(
                f"  {op.name}: {op.wall_s * 1e3:.1f}ms, {op.num_outputs} blocks, {op.output_rows} rows"
            )
        return "\n".join(lines)
