"""Physical execution: streaming executor over ray_tpu tasks/actors.

Capability parity: reference python/ray/data/_internal/execution/ — StreamingExecutor
(streaming_executor.py:52), TaskPoolMapOperator / ActorPoolMapOperator
(operators/*.py), backpressure policies. Map stages stream block-by-block with bounded
in-flight tasks; all-to-all stages (sort/shuffle/aggregate/repartition) are barriers,
as in the reference.
"""
from __future__ import annotations

import itertools
import os
import time
import types
import zlib
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu

from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from . import logical as L
from .stats import DatasetStats, OpStats

# ---- UDF application (runs inside worker tasks) -----------------------------


def _apply_one_spec(spec: L.MapSpec, block: Block, fn_impl) -> Block:
    acc = BlockAccessor.for_block(block)
    kind = spec.kind
    if kind == "map_batches":
        out_blocks = []
        n = acc.num_rows()
        bs = spec.batch_size or n or 1
        for start in range(0, max(n, 1), bs) if n else []:
            batch = BlockAccessor.for_block(acc.slice(start, min(start + bs, n))).to_batch_format(spec.batch_format)
            res = fn_impl(batch, *spec.fn_args, **spec.fn_kwargs)
            if isinstance(res, types.GeneratorType) or (
                hasattr(res, "__next__") and not isinstance(res, (dict, list, pa.Table))
            ):
                for r in res:
                    out_blocks.append(BlockAccessor.batch_to_block(r))
            else:
                out_blocks.append(BlockAccessor.batch_to_block(res))
        return BlockAccessor.concat(out_blocks)
    if kind == "map_rows":
        rows = [fn_impl(r, *spec.fn_args, **spec.fn_kwargs) for r in acc.iter_rows()]
        return pa.Table.from_pylist(rows) if rows else BlockAccessor.empty()
    if kind == "flat_map":
        rows = []
        for r in acc.iter_rows():
            rows.extend(fn_impl(r, *spec.fn_args, **spec.fn_kwargs))
        return pa.Table.from_pylist(rows) if rows else BlockAccessor.empty()
    if kind == "filter":
        mask = np.array([bool(fn_impl(r, *spec.fn_args, **spec.fn_kwargs)) for r in acc.iter_rows()])
        return acc.take(np.nonzero(mask)[0]) if len(mask) else block
    if kind == "add_column":
        name, = spec.fn_args
        col = fn_impl(acc.to_batch_format("numpy"))
        return block.append_column(name, pa.array(np.asarray(col)))
    if kind == "drop_columns":
        return block.drop_columns(list(spec.fn_args[0]))
    if kind == "select_columns":
        return block.select(list(spec.fn_args[0]))
    if kind == "rename_columns":
        mapping = spec.fn_args[0]
        return block.rename_columns([mapping.get(c, c) for c in block.column_names])
    raise ValueError(f"unknown map kind {kind}")


def _resolve_fn(spec: L.MapSpec, instances: Dict[int, Any], idx: int):
    if isinstance(spec.fn, type):  # class-based UDF -> instantiate once per worker
        if idx not in instances:
            instances[idx] = spec.fn(*spec.fn_constructor_args, **spec.fn_constructor_kwargs)
        return instances[idx]
    return spec.fn


def _map_block(specs: List[L.MapSpec], block: Block) -> Tuple[Block, BlockMetadata]:
    instances: Dict[int, Any] = {}
    for i, spec in enumerate(specs):
        block = _apply_one_spec(spec, block, _resolve_fn(spec, instances, i))
    return block, BlockAccessor.for_block(block).get_metadata()


class _MapWorker:
    """Actor-pool UDF host (reference actor_pool_map_operator.py:_MapWorker)."""

    def __init__(self, specs: List[L.MapSpec]):
        self.specs = specs
        self.instances: Dict[int, Any] = {}
        for i, spec in enumerate(self.specs):  # eager init so failures surface at pool start
            _resolve_fn(spec, self.instances, i)

    def ready(self) -> bool:
        return True

    def map_block(self, block: Block) -> Tuple[Block, BlockMetadata]:
        for i, spec in enumerate(self.specs):
            block = _apply_one_spec(spec, block, _resolve_fn(spec, self.instances, i))
        return block, BlockAccessor.for_block(block).get_metadata()


def _read_task_fn(read_fn, specs: List[L.MapSpec]):
    blocks = list(read_fn())
    block = BlockAccessor.concat(blocks) if len(blocks) != 1 else blocks[0]
    return _map_block(specs, block) if specs else (block, BlockAccessor.for_block(block).get_metadata())


def _write_block(datasink, block: Block, task_index: int) -> Tuple[str, int]:
    path = datasink.write(block, task_index)
    return path, block.num_rows


# ---- all-to-all kernels (run as tasks) --------------------------------------


def _partition_by_boundaries(block: Block, key: str, boundaries: List[Any]) -> List[Block]:
    """Ascending range-partition; descending order is applied at merge time."""
    acc = BlockAccessor.for_block(block)
    sorted_block = acc.sort(key, descending=False)
    col = BlockAccessor.for_block(sorted_block).to_numpy([key])[key]
    cuts = [int(i) for i in np.searchsorted(col, boundaries, side="left")]
    parts, prev = [], 0
    for c in cuts + [len(col)]:
        parts.append(BlockAccessor.for_block(sorted_block).slice(prev, c))
        prev = c
    return parts


def _merge_sorted(key: str, descending: bool, *parts: Block) -> Tuple[Block, BlockMetadata]:
    merged = BlockAccessor.concat(list(parts))
    if BlockAccessor.for_block(merged).num_rows() == 0 and parts:
        # concat() drops 0-row blocks; an all-empty partition (few distinct sort
        # keys across many blocks) must keep its schema so sort_by still resolves
        merged = BlockAccessor.for_block(parts[0]).slice(0, 0)
    merged = BlockAccessor.for_block(merged).sort(key, descending)
    return merged, BlockAccessor.for_block(merged).get_metadata()


def _random_split_block(block: Block, n_out: int, seed: int, salt: int = 0) -> List[Block]:
    rng = np.random.default_rng((seed, salt))
    acc = BlockAccessor.for_block(block)
    assign = rng.integers(0, n_out, size=acc.num_rows())
    return [acc.take(np.nonzero(assign == p)[0]) for p in range(n_out)]


def _merge_shuffled(seed: int, *parts: Block) -> Tuple[Block, BlockMetadata]:
    merged = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(merged)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(acc.num_rows())
    out = acc.take(perm)
    return out, BlockAccessor.for_block(out).get_metadata()


def _concat_blocks(*parts: Block) -> Block:
    """Merge-stage combiner for the push-based shuffle: plain row-union concat.
    Every exchange reduce here (_merge_sorted / _merge_shuffled /
    _agg_partition) is a function of the row UNION of its parts, so pre-
    concatenating partials is semantics-preserving. An all-empty partition
    (few distinct sort keys -> repeated boundaries) must keep its SCHEMA: the
    downstream reduce sorts/groups by column name, and concat of zero-row
    parts would otherwise collapse to a column-less table."""
    non_empty = [p for p in parts if BlockAccessor.for_block(p).num_rows() > 0]
    if not non_empty:
        return parts[0]  # zero rows, schema intact
    return BlockAccessor.concat(non_empty)


def _hash_partition(block: Block, key: str, n_out: int) -> List[Block]:
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy([key])[key]
    # Deterministic across worker processes (Python hash() is per-process salted).
    hashes = np.array(
        [zlib.crc32(repr(v).encode()) % n_out for v in col.tolist()], dtype=np.int64
    )
    return [acc.take(np.nonzero(hashes == p)[0]) for p in range(n_out)]


def _slice_concat(ranges: List[Tuple[int, int, int]], *blocks: Block) -> Tuple[Block, BlockMetadata]:
    """Assemble one output block from [(input_idx, start, end)] row ranges."""
    parts = [BlockAccessor.for_block(blocks[i]).slice(s, e) for i, s, e in ranges]
    out = BlockAccessor.concat(parts) if any(p.num_rows for p in parts) else parts[0]
    return out, BlockAccessor.for_block(out).get_metadata()


def _zip_blocks(left: Block, right: Block) -> Tuple[Block, BlockMetadata]:
    for name in right.column_names:
        col = right.column(name)
        out_name = name if name not in left.column_names else f"{name}_1"
        left = left.append_column(out_name, col)
    return left, BlockAccessor.for_block(left).get_metadata()


def _join_partition(on: str, how: str, n_left: int, *parts: Block) -> Tuple[Block, BlockMetadata]:
    """Hash-join one co-partition: first n_left blocks are the left side.

    Arrow take() with null indices materializes the outer-join null rows, so
    nullability is real Arrow nulls, not sentinel values."""
    import pyarrow as pa

    def concat_keep_schema(blocks):
        """concat() drops 0-row blocks (and with them the schema outer joins
        need for null columns); fall back to the first block's schema."""
        if not blocks:
            return None
        merged = BlockAccessor.concat(list(blocks))
        if merged.num_rows == 0:
            merged = blocks[0].slice(0, 0)
        return BlockAccessor.for_block(merged).to_arrow()

    lt = concat_keep_schema(parts[:n_left])
    rt = concat_keep_schema(parts[n_left:])
    if lt is None and rt is None:
        out = BlockAccessor.empty()
        return out, BlockAccessor.for_block(out).get_metadata()
    if lt is None:
        out = rt if how in ("right_outer", "full_outer") else rt.slice(0, 0)
        return out, BlockAccessor.for_block(out).get_metadata()
    if rt is None:
        out = lt if how in ("left_outer", "full_outer") else lt.slice(0, 0)
        return out, BlockAccessor.for_block(out).get_metadata()

    from collections import defaultdict

    right_index = defaultdict(list)
    for j, v in enumerate(rt.column(on).to_pylist()):
        if v is not None:  # SQL semantics: null keys never match
            right_index[v].append(j)
    li: List[Optional[int]] = []
    ri: List[Optional[int]] = []
    matched = set()
    for i, v in enumerate(lt.column(on).to_pylist()):
        js = right_index.get(v) if v is not None else None
        if js:
            for j in js:
                li.append(i)
                ri.append(j)
                matched.add(j)
        elif how in ("left_outer", "full_outer"):
            li.append(i)
            ri.append(None)
    if how in ("right_outer", "full_outer"):
        for j in range(rt.num_rows):
            if j not in matched:
                li.append(None)
                ri.append(j)
    li_arr = pa.array(li, type=pa.int64())
    ri_arr = pa.array(ri, type=pa.int64())
    ltak = lt.take(li_arr)
    rtak = rt.take(ri_arr)
    import pyarrow.compute as pc

    names, cols = [on], [pc.coalesce(ltak.column(on).combine_chunks(),
                                     rtak.column(on).combine_chunks())]
    for name in lt.column_names:
        if name != on:
            names.append(name)
            cols.append(ltak.column(name))
    for name in rt.column_names:
        if name != on:
            # uniquify collisions: "_1" alone can itself collide with an existing
            # left column (e.g. left has v and v_1), and the dict() below would
            # silently drop one of them
            unique = name
            suffix = 1
            while unique in names:
                unique = f"{name}_{suffix}"
                suffix += 1
            names.append(unique)
            cols.append(rtak.column(name))
    out = pa.table(dict(zip(names, cols)))
    return out, BlockAccessor.for_block(out).get_metadata()


def _agg_partition(key: Optional[str], aggs, *parts: Block) -> Tuple[Block, BlockMetadata]:
    from .aggregate import aggregate_block

    merged = BlockAccessor.concat(list(parts))
    out = aggregate_block(merged, key, aggs)
    return out, BlockAccessor.for_block(out).get_metadata()


# ---- executor ----------------------------------------------------------------

_remote_cache: Dict[Tuple, Any] = {}


def _remote(fn, **opts):
    k = (fn.__name__, tuple(sorted(opts.items())))
    if k not in _remote_cache:
        _remote_cache[k] = ray_tpu.remote(**({"num_cpus": 1} | opts))(fn)
    return _remote_cache[k]


RefBundle = Tuple[Any, BlockMetadata]  # (ObjectRef[Block] | Block, metadata)


class StreamingExecutor:
    """Lower an optimized logical plan and run it as a pull-based operator
    topology (reference streaming_executor.py:52 + streaming_executor_state.py).

    Every operator is a generator over RefBundles consuming its upstream
    generator: downstream tasks start as soon as ANY upstream bundle lands —
    no barrier between stages. Read/map/write stages keep at most
    ctx.max_inflight_tasks_per_op tasks in flight (per-op backpressure);
    all-to-all stages (sort/shuffle/join/...) inherently consume their whole
    input before producing.
    """

    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        self.stats = DatasetStats()
        # nesting ledger for exclusive per-op wall time: pulling a downstream
        # op transitively produces upstream, so inclusive timing would charge
        # the read's seconds to every later stage too
        self._time_stack: List[float] = []

    # -- public ---------------------------------------------------------------
    def execute(self, plan: L.LogicalOperator) -> List[RefBundle]:
        return list(self.execute_iter(plan))

    def execute_iter(self, plan: L.LogicalOperator) -> Iterator[RefBundle]:
        """Lazily yield output bundles while upstream operators keep running."""
        plan = L.optimize(plan)
        chain = plan.chain()
        self._budget_actor_pools(chain)
        stream: Iterator[RefBundle] = iter(())
        for op in chain:
            stream = self._op_iter(op, stream)
        return stream

    def _requested_pool_size(self, op: L.AbstractMap) -> int:
        conc = op.concurrency
        if isinstance(conc, tuple):
            return max(1, int(conc[1]))
        if isinstance(conc, int):
            return max(1, conc)
        return max(1, self.ctx.actor_pool_max_size)

    def _budget_actor_pools(self, chain: List[L.LogicalOperator]) -> None:
        """Apportion cluster CPUs across ALL actor-pool stages before any pool
        exists. Pools are created in pull order (downstream first) and their
        idle actors hold CPUs until the pipeline ends, so sizing each pool
        against free-at-creation CPUs can leave an upstream pool's ready()
        barrier waiting forever on a downstream pool's idle actors. Budgeting
        top-down guarantees the sum of pool sizes fits the cluster; if even one
        1-CPU actor per pool can't fit, pools fall back to 0-CPU actors
        (oversubscribe rather than deadlock)."""
        pools = [op for op in chain
                 if isinstance(op, L.AbstractMap) and op.compute == "actors"]
        if not pools:
            return
        # capacity = CPUs actually free right now: CPUs pinned by actors
        # OUTSIDE this pipeline (serve replicas, user actors) are never coming
        # back, and a pool sized past what can schedule would stall
        total = int(ray_tpu.available_resources().get("CPU", 0.0))
        # task-compute stages (reads, task maps, shuffles) submit 1-CPU tasks
        # that must stay schedulable while every pool actor idles
        has_task_stage = any(not (isinstance(op, L.AbstractMap)
                                  and op.compute == "actors")
                             and not isinstance(op, L.InputData)
                             for op in chain)
        budget_total = total - (1 if has_task_stage else 0)
        reqs = [self._requested_pool_size(op) for op in pools]
        # per-actor CPU request (user num_cpus overrides the 1 default)
        pers = [max(0.0, float(op.ray_remote_args.get("num_cpus", 1)))
                for op in pools]
        if sum(pers) > budget_total:
            # even one actor per pool can't be co-scheduled: fall back to ONE
            # 0-CPU actor per pool — schedulable regardless of CPU pressure and
            # bounded so the worker-process cap (max_workers_per_node) still
            # leaves room for task-stage workers
            for op in pools:
                op._pool_budget, op._pool_cpus = 1, 0
            return
        remaining = float(budget_total)
        for i, (op, r, per) in enumerate(zip(pools, reqs, pers)):
            later_min = sum(pers[i + 1:])  # later pools each need >= 1 actor
            max_actors = int((remaining - later_min) / per) if per > 0 else r
            give = max(1, min(r, max_actors))
            op._pool_budget, op._pool_cpus = give, per
            remaining -= give * per

    # -- per-op dispatch ------------------------------------------------------
    def _op_iter(self, op: L.LogicalOperator, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        if isinstance(op, L.InputData):
            gen: Iterator[RefBundle] = iter(list(zip(op.blocks, op.metadata)))
        elif isinstance(op, L.Read):
            gen = self._read_iter(op)
        elif isinstance(op, L.AbstractMap):
            gen = self._map_iter(op, upstream)
        elif isinstance(op, L.Limit):
            gen = self._limit_iter(op, upstream)
        elif isinstance(op, L.Union):
            gen = self._union_iter(op, upstream)
        elif isinstance(op, L.Write):
            gen = self._write_iter(op, upstream)
        else:
            gen = self._all_to_all_iter(op, upstream)
        return self._with_stats(op.name, gen)

    def _with_stats(self, name: str, gen: Iterator[RefBundle]) -> Iterator[RefBundle]:
        """Track per-op EXCLUSIVE wall time (producing, minus time spent inside
        upstream wrappers) + output counts; records stats even when the consumer
        stops early (take/limit)."""
        wall = 0.0
        n = 0
        rows = 0

        def timed_next():
            nonlocal wall
            t0 = time.perf_counter()
            self._time_stack.append(0.0)
            try:
                return next(gen)
            finally:
                dt = time.perf_counter() - t0
                upstream_dt = self._time_stack.pop()
                wall += dt - upstream_dt
                if self._time_stack:
                    self._time_stack[-1] += dt

        try:
            while True:
                try:
                    bundle = timed_next()
                except StopIteration:
                    return
                n += 1
                if bundle[1].num_rows >= 0:
                    rows += bundle[1].num_rows
                yield bundle
        finally:
            self.stats.ops.append(
                OpStats(name=name, wall_s=wall, num_outputs=n, output_rows=rows))

    # -- streaming stages ------------------------------------------------------
    def _stream_tasks_iter(self, thunks: Iterator[Any]) -> Iterator[RefBundle]:
        """Bounded-in-flight task pump: pull a thunk (which may lazily pull the
        upstream stage), submit, and yield completed bundles in input order.
        Pulling thunks only while under the cap IS the backpressure — a slow
        downstream stops draining, this op stops submitting, and its upstream
        stops being pulled (reference backpressure_policy/).

        Each thunk submits a num_returns=2 task -> (block_ref, meta_ref). Only
        metadata is fetched to the driver; blocks stay in the object store.
        """
        cap = self.ctx.max_inflight_tasks_per_op
        results: Dict[int, RefBundle] = {}
        inflight: Dict[Any, Tuple[int, Any]] = {}
        next_submit = 0
        next_yield = 0
        exhausted = False
        while True:
            while not exhausted and len(inflight) < cap:
                try:
                    thunk = next(thunks)
                except StopIteration:
                    exhausted = True
                    break
                block_ref, meta_ref = thunk()
                inflight[meta_ref] = (next_submit, block_ref)
                next_submit += 1
            while next_yield in results:
                yield results.pop(next_yield)
                next_yield += 1
            if not inflight:
                if exhausted and next_yield >= next_submit:
                    return
                continue
            done, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=10.0)
            for meta_ref in done:
                i, block_ref = inflight.pop(meta_ref)
                results[i] = (block_ref, ray_tpu.get(meta_ref))

    def _read_iter(self, op: L.Read) -> Iterator[RefBundle]:
        parallelism = op.parallelism if op.parallelism > 0 else self.ctx.read_op_min_num_blocks
        read_tasks = op.datasource.get_read_tasks(parallelism)
        fused_specs = getattr(op, "_fused_specs", [])
        remote_read = _remote(_read_task_fn).options(num_returns=2)
        return self._stream_tasks_iter(
            (lambda rt=rt: remote_read.remote(rt.fn, fused_specs)) for rt in read_tasks
        )

    def _map_iter(self, op: L.AbstractMap, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        opts = {k: v for k, v in op.ray_remote_args.items() if k in ("num_cpus", "num_tpus", "resources")}
        if op.compute == "actors":
            return self._actor_pool_map_iter(op, upstream, opts)
        remote_map = _remote(_map_block, **opts).options(num_returns=2)
        return self._stream_tasks_iter(
            (lambda b=b: remote_map.remote(op.specs, b)) for b, _ in upstream
        )

    def _actor_pool_map_iter(self, op: L.AbstractMap, upstream: Iterator[RefBundle],
                             opts) -> Iterator[RefBundle]:
        pool_size = getattr(op, "_pool_budget", None)
        pool_cpus = getattr(op, "_pool_cpus", 1)
        if pool_size is None:  # op ran outside execute_iter's budgeting pass
            total = int(ray_tpu.cluster_resources().get("CPU", 1.0))
            pool_size = max(1, min(self._requested_pool_size(op), total))
        worker_opts = {"num_cpus": pool_cpus} | opts
        if pool_cpus == 0:
            worker_opts["num_cpus"] = 0  # overflow pools must stay schedulable
        Worker = ray_tpu.remote(**worker_opts)(_MapWorker)
        actors = [Worker.remote(op.specs) for _ in range(pool_size)]
        # NO all-ready barrier: actors join the idle set as they come up, so a
        # pool partially starved by external CPU pressure still makes progress
        # with whatever subset schedules (the budget makes >=1 the common case)
        pending_ready = {a.ready.remote(): a for a in actors}
        try:
            results: Dict[int, RefBundle] = {}
            idle: deque = deque()
            inflight: Dict[Any, Tuple[int, Any, Any]] = {}
            next_submit = 0
            next_yield = 0
            exhausted = False
            while True:
                if pending_ready:
                    # block only when there is work to do and nothing to do it
                    # with; 0 = opportunistic drain of newly-up actors
                    timeout = None if not (idle or inflight or exhausted) else 0
                    done, _ = ray_tpu.wait(list(pending_ready),
                                           num_returns=1, timeout=timeout)
                    for r in done:
                        idle.append(pending_ready.pop(r))
                while not exhausted and idle:
                    try:
                        b, _ = next(upstream)
                    except StopIteration:
                        exhausted = True
                        break
                    actor = idle.popleft()
                    block_ref, meta_ref = actor.map_block.options(num_returns=2).remote(b)
                    inflight[meta_ref] = (next_submit, actor, block_ref)
                    next_submit += 1
                while next_yield in results:
                    yield results.pop(next_yield)
                    next_yield += 1
                if not inflight:
                    if exhausted and next_yield >= next_submit:
                        return
                    continue
                done, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=10.0)
                for meta_ref in done:
                    i, actor, block_ref = inflight.pop(meta_ref)
                    idle.append(actor)
                    results[i] = (block_ref, ray_tpu.get(meta_ref))
        finally:
            for a in actors:
                ray_tpu.kill(a)

    def _limit_iter(self, op: L.Limit, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        remaining = op.limit
        if remaining <= 0:
            return
        for b, m in upstream:
            n = m.num_rows if m.num_rows >= 0 else BlockAccessor.for_block(ray_tpu.get(b)).num_rows()
            if n <= remaining:
                yield (b, m)
                remaining -= n
            else:
                block = BlockAccessor.for_block(ray_tpu.get(b)).slice(0, remaining)
                yield (ray_tpu.put(block), BlockAccessor.for_block(block).get_metadata())
                remaining = 0
            if remaining <= 0:
                # return BEFORE pulling again: one more next(upstream) would
                # submit (and block on) a full window of unneeded upstream tasks
                return

    def _union_iter(self, op: L.Union, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        yield from upstream
        for other in op.others:
            yield from StreamingExecutor(self.ctx).execute_iter(other)

    def _write_iter(self, op: L.Write, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        remote_write = _remote(_write_block)
        counter = itertools.count()
        cap = self.ctx.max_inflight_tasks_per_op
        inflight: deque = deque()
        for b, _ in upstream:
            inflight.append(remote_write.remote(op.datasink, b, next(counter)))
            if len(inflight) >= cap:
                path, rows = ray_tpu.get(inflight.popleft())
                yield (ray_tpu.put(pa.table({"path": [path], "num_rows": [rows]})),
                       BlockMetadata(1, 0))
        while inflight:
            path, rows = ray_tpu.get(inflight.popleft())
            yield (ray_tpu.put(pa.table({"path": [path], "num_rows": [rows]})),
                   BlockMetadata(1, 0))

    # -- all-to-all (inherent barrier on input) --------------------------------
    def _all_to_all_iter(self, op: L.LogicalOperator, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
        inputs = list(upstream)
        if isinstance(op, L.Sort):
            out = self._run_sort(op, inputs)
        elif isinstance(op, L.RandomShuffle):
            out = self._run_shuffle(op, inputs)
        elif isinstance(op, L.Repartition):
            out = self._run_repartition(op, inputs)
        elif isinstance(op, L.Aggregate):
            out = self._run_aggregate(op, inputs)
        elif isinstance(op, L.Join):
            out = self._run_join(op, inputs)
        elif isinstance(op, L.Zip):
            out = self._run_zip(op, inputs)
        else:
            raise NotImplementedError(f"op {op}")
        yield from out

    # -- all-to-all ------------------------------------------------------------
    def _sample_boundaries(self, inputs: List[RefBundle], key: str, n_parts: int) -> List[Any]:
        samples = []
        for b, _ in inputs[: max(n_parts * 2, 8)]:
            block = ray_tpu.get(b)
            acc = BlockAccessor.for_block(block)
            if acc.num_rows():
                s = acc.sample(min(32, acc.num_rows()), seed=0)
                samples.append(BlockAccessor.for_block(s).to_numpy([key])[key])
        if not samples:
            return []
        allv = np.sort(np.concatenate(samples))
        return [allv[int(len(allv) * (i + 1) / n_parts) - 1] for i in range(n_parts - 1)]

    def _two_phase(self, inputs, map_fn, map_args, reduce_fn, reduce_args, n_parts) -> List[RefBundle]:
        """Generic shuffle: map each block into n_parts partitions, reduce per-partition.

        Partition blocks and reduced outputs stay in the object store; the driver only
        routes refs (map side: num_returns=n_parts, reduce side: num_returns=2).

        Pull-based (default): every reduce task fans in ALL n_map partition refs
        at once — simple, but peak memory is the full map output and reduce
        can't start until the last map finishes. Push-based
        (DataContext.use_push_based_shuffle; reference
        push_based_shuffle_task_scheduler.py): map tasks run in rounds of
        `merge_factor`, and each round's partitions are eagerly folded into a
        running per-partition merge — fan-in is bounded by merge_factor+1,
        merges of round r overlap maps of round r+1, and a round's map outputs
        become garbage as soon as its merges finish. The final reduce consumes
        ONE merged block per partition.
        """
        from .context import DataContext

        ctx = DataContext.get_current()
        rreduce = _remote(reduce_fn).options(num_returns=2)
        out = []
        reduce_refs = []
        if n_parts == 1:
            # Single partition: the map phase is a no-op, reduce over the raw blocks.
            reduce_refs.append(rreduce.remote(*reduce_args, *[b for b, _ in inputs]))
        elif ctx.use_push_based_shuffle and len(inputs) > 2:
            rmap = _remote(map_fn).options(num_returns=n_parts)
            rmerge = _remote(_concat_blocks)
            per_index_args = map_args if callable(map_args) else (lambda i: map_args)
            factor = max(2, int(getattr(ctx, "push_shuffle_merge_factor", 8)))
            merged: List[Optional[Any]] = [None] * n_parts
            items = list(enumerate(inputs))
            for start in range(0, len(items), factor):
                round_items = items[start:start + factor]
                part_refs = [rmap.remote(b, *per_index_args(i))
                             for i, (b, _) in round_items]
                for p in range(n_parts):
                    parts = [pl[p] for pl in part_refs]
                    if merged[p] is not None:
                        parts.insert(0, merged[p])
                    merged[p] = (parts[0] if len(parts) == 1
                                 else rmerge.remote(*parts))
            for p in range(n_parts):
                reduce_refs.append(rreduce.remote(*reduce_args, merged[p]))
        else:
            rmap = _remote(map_fn).options(num_returns=n_parts)
            per_index_args = map_args if callable(map_args) else (lambda i: map_args)
            part_refs = [rmap.remote(b, *per_index_args(i)) for i, (b, _) in enumerate(inputs)]
            for p in range(n_parts):
                parts = [pl[p] for pl in part_refs]
                reduce_refs.append(rreduce.remote(*reduce_args, *parts))
        for block_ref, meta_ref in reduce_refs:
            out.append((block_ref, ray_tpu.get(meta_ref)))
        return out

    def _run_sort(self, op: L.Sort, inputs: List[RefBundle]) -> List[RefBundle]:
        if not inputs:
            return []
        n_parts = max(1, len(inputs))
        boundaries = self._sample_boundaries(inputs, op.key, n_parts)
        n_parts = len(boundaries) + 1
        out = self._two_phase(
            inputs,
            _partition_by_boundaries, (op.key, boundaries),
            _merge_sorted, (op.key, op.descending),
            n_parts,
        )
        return out[::-1] if op.descending else out

    def _run_shuffle(self, op: L.RandomShuffle, inputs: List[RefBundle]) -> List[RefBundle]:
        if not inputs:
            return []
        n_parts = len(inputs)
        seed = op.seed if op.seed is not None else int.from_bytes(os.urandom(4), "little")
        return self._two_phase(
            inputs, _random_split_block, lambda i: (n_parts, seed, i), _merge_shuffled, (seed,), n_parts
        )

    def _block_rows(self, inputs: List[RefBundle]) -> List[int]:
        rows = []
        for b, m in inputs:
            if m.num_rows >= 0:
                rows.append(m.num_rows)
            else:
                rows.append(BlockAccessor.for_block(ray_tpu.get(b)).num_rows())
        return rows

    def _slice_to_layout(self, inputs: List[RefBundle], sizes: List[int]) -> List[RefBundle]:
        """Re-chunk inputs into blocks of the given sizes via worker-side slice tasks."""
        rows = self._block_rows(inputs)
        rslice = _remote(_slice_concat).options(num_returns=2)
        # walk (input_idx, offset) across the concatenated row space
        out, ii, off = [], 0, 0
        refs = [b for b, _ in inputs]
        for size in sizes:
            ranges, need = [], size
            while need > 0 and ii < len(rows):
                take = min(need, rows[ii] - off)
                if take > 0:
                    ranges.append((ii, off, off + take))
                    off += take
                    need -= take
                if off >= rows[ii]:
                    ii += 1
                    off = 0
            if not ranges and refs:
                ranges = [(0, 0, 0)]  # empty shard keeps the schema of block 0
            # remap input indices to the compact arg list for this task
            uniq = sorted(set(i for i, _, _ in ranges))
            remap = {g: l for l, g in enumerate(uniq)}
            local_ranges = [(remap[i], s, e) for i, s, e in ranges]
            block_ref, meta_ref = rslice.remote(local_ranges, *[refs[g] for g in uniq])
            out.append((block_ref, ray_tpu.get(meta_ref)))
        return out

    def _run_repartition(self, op: L.Repartition, inputs: List[RefBundle]) -> List[RefBundle]:
        n = sum(self._block_rows(inputs))
        k = max(1, op.num_blocks)
        per, rem = n // k, n % k
        sizes = [per + (1 if i < rem else 0) for i in range(k)]
        return self._slice_to_layout(inputs, sizes)

    def _run_aggregate(self, op: L.Aggregate, inputs: List[RefBundle]) -> List[RefBundle]:
        if not inputs:
            return []
        if op.key is None:  # global aggregate: single reduce
            rreduce = _remote(_agg_partition).options(num_returns=2)
            block_ref, meta_ref = rreduce.remote(None, op.aggs, *[b for b, _ in inputs])
            return [(block_ref, ray_tpu.get(meta_ref))]
        n_parts = min(len(inputs), 8)
        return self._two_phase(inputs, _hash_partition, (op.key, n_parts), _agg_partition, (op.key, op.aggs), n_parts)

    def _run_join(self, op: L.Join, inputs: List[RefBundle]) -> List[RefBundle]:
        """Hash-shuffle both sides on the key, then join co-partitions in tasks
        (reference operators/join.py over hash_shuffle.py)."""
        right = StreamingExecutor(self.ctx).execute(op.other)
        if not inputs and not right:
            return []
        n_parts = op.num_partitions or max(len(inputs), len(right), 1)
        rjoin = _remote(_join_partition).options(num_returns=2)
        if n_parts == 1:
            block_ref, meta_ref = rjoin.remote(
                op.on, op.how, len(inputs), *[b for b, _ in inputs], *[b for b, _ in right])
            return [(block_ref, ray_tpu.get(meta_ref))]
        rmap = _remote(_hash_partition).options(num_returns=n_parts)
        left_parts = [rmap.remote(b, op.on, n_parts) for b, _ in inputs]
        right_parts = [rmap.remote(b, op.on, n_parts) for b, _ in right]
        # submit every partition's join before touching metadata so they run in parallel
        pairs = []
        for p in range(n_parts):
            lrefs = [pl[p] for pl in left_parts]
            rrefs = [pl[p] for pl in right_parts]
            pairs.append(rjoin.remote(op.on, op.how, len(lrefs), *lrefs, *rrefs))
        return [(block_ref, ray_tpu.get(meta_ref)) for block_ref, meta_ref in pairs]

    def _run_zip(self, op: L.Zip, inputs: List[RefBundle]) -> List[RefBundle]:
        other = StreamingExecutor(self.ctx).execute(op.other)
        left_rows = self._block_rows(inputs)
        right_rows = self._block_rows(other)
        if sum(left_rows) != sum(right_rows):
            raise ValueError(f"zip row mismatch: {sum(left_rows)} vs {sum(right_rows)}")
        # align the right side to the left block layout, then zip block pairs in tasks
        aligned = self._slice_to_layout(other, left_rows)
        rzip = _remote(_zip_blocks).options(num_returns=2)
        pairs = [rzip.remote(lb, rb) for (lb, _), (rb, _) in zip(inputs, aligned)]
        return [(block_ref, ray_tpu.get(meta_ref)) for block_ref, meta_ref in pairs]
