"""DataIterator: batch iteration with prefetch.

Capability parity: reference python/ray/data/iterator.py (iter_batches/iter_rows/
iter_torch_batches) + _internal/block_batching/. Prefetch pipelines object-store fetches
one block ahead of consumption — the pattern that keeps the TPU fed during training.
"""
from __future__ import annotations

import threading
import queue as _queue
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import BlockAccessor


class DataIterator:
    """Iterates batches over (block_ref, metadata) bundles — a materialized list
    OR a live execute_iter() generator, in which case batches yield while
    upstream operators are still producing (reference iter_batches streaming)."""

    def __init__(self, bundles: Any):
        self._bundles = bundles
        self._consumed = False

    def _iter_blocks(self, prefetch_blocks: int = 1):
        if self._consumed and not isinstance(self._bundles, (list, tuple)):
            raise RuntimeError(
                "this DataIterator streams a live execution and was already "
                "consumed; call Dataset.iterator() again (re-executes) or "
                "Dataset.materialize() first for multi-epoch iteration")
        self._consumed = True
        q: _queue.Queue = _queue.Queue(maxsize=max(1, prefetch_blocks))
        SENTINEL = object()
        stop = threading.Event()

        def offer(item) -> bool:
            """put() that gives up when the consumer abandoned us."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def producer():
            try:
                for r, _ in self._bundles:
                    if not offer(ray_tpu.get(r)):
                        break
                else:
                    offer(SENTINEL)
            except BaseException as e:  # noqa: BLE001 - re-raised in the consumer
                offer(e)
            finally:
                # ALWAYS close the live execution generator HERE (this thread is
                # its only driver) so every stage's finally runs — actor pools
                # killed, stats recorded. Covers early consumer abandonment AND
                # a mid-stream task failure; a no-op on exhausted generators.
                close = getattr(self._bundles, "close", None)
                if close is not None:
                    try:
                        close()
                    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                    except Exception:
                        pass

        t = threading.Thread(target=producer, daemon=True,
                             name="data-iter-producer")
        t.start()
        try:
            while True:
                item = q.get()
                if item is SENTINEL:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            try:
                q.get_nowait()  # wake a producer blocked mid-put
            except _queue.Empty:
                pass

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_blocks: int = 1,
    ) -> Iterator[Any]:
        carry = None  # leftover rows spanning block boundaries (arrow table)
        rng = np.random.default_rng(local_shuffle_seed)
        for block in self._iter_blocks(prefetch_blocks):
            if carry is not None and carry.num_rows:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if batch_size is None:
                yield acc.to_batch_format(batch_format)
                continue
            if local_shuffle_buffer_size and n:
                perm = rng.permutation(n)
                block = acc.take(perm)
                acc = BlockAccessor.for_block(block)
            start = 0
            while n - start >= batch_size:
                yield BlockAccessor.for_block(acc.slice(start, start + batch_size)).to_batch_format(batch_format)
                start += batch_size
            if start < n:
                carry = acc.slice(start, n)
        if carry is not None and carry.num_rows and not drop_last and batch_size is not None:
            yield BlockAccessor.for_block(carry).to_batch_format(batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256, **kw) -> Iterator[Dict[str, Any]]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", **kw):
            yield {k: torch.as_tensor(v) for k, v in batch.items() if v.dtype != object}

    def iter_jax_batches(
        self, *, batch_size: Optional[int] = 256, sharding=None, **kw
    ) -> Iterator[Dict[str, Any]]:
        """TPU-native: yield device-resident jax.Arrays, optionally pre-sharded.

        With a NamedSharding, each batch lands distributed across the mesh without a
        host-side gather — the iter path the JaxTrainer uses for data-parallel ingest.
        """
        import jax

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", **kw):
            arrs = {k: v for k, v in batch.items() if v.dtype != object}
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in arrs.items()}
            else:
                yield {k: jax.device_put(v) for k, v in arrs.items()}
