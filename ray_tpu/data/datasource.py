"""Datasources and datasinks.

Capability parity: reference python/ray/data/datasource/ + _internal/datasource/
(parquet/csv/json/range/binary read; parquet/csv/json write). A Datasource yields
ReadTasks — serializable thunks each producing one block — which the executor schedules
as ray_tpu tasks.
"""
from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockAccessor, BlockMetadata


@dataclasses.dataclass
class ReadTask:
    """One schedulable unit of reading; fn() -> iterable of Blocks."""

    fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata


class Datasource:
    """ABC (reference datasource.py:Datasource)."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


def _object_column(vals: List[Any]) -> np.ndarray:
    """Ragged/mixed values -> 1-D object array. np.asarray(..., dtype=object)
    raises on inhomogeneous ndarray elements; element-wise fill never does."""
    out = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        out[i] = v
    return out


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files) if not f.startswith("."))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files found for {paths}")
    return out


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self.n = n
        self.column = column

    def estimate_inmemory_data_size(self):
        return self.n * 8

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        tasks = []
        per = self.n // parallelism
        rem = self.n % parallelism
        start = 0
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            s, e, col = start, start + cnt, self.column

            def fn(s=s, e=e, col=col):
                yield pa.table({col: np.arange(s, e, dtype=np.int64)})

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=cnt * 8)))
            start += cnt
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        tasks = []
        per, rem, start = n // parallelism, n % parallelism, 0
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            chunk = self.items[start : start + cnt]

            def fn(chunk=chunk):
                if chunk and isinstance(chunk[0], dict):
                    if any(isinstance(v, np.ndarray) and v.ndim >= 1
                           for r in chunk for v in r.values()):
                        # tensor-valued rows: from_pylist can't nest multi-dim
                        # ndarrays — assemble columns so batch_to_block makes
                        # FixedSizeList tensor columns
                        cols = {}
                        for c in chunk[0]:
                            vals = [r.get(c) for r in chunk]
                            shapes = {v.shape for v in vals
                                      if isinstance(v, np.ndarray)}
                            if len(shapes) == 1 and all(
                                    isinstance(v, np.ndarray) for v in vals):
                                cols[c] = np.stack(vals)
                            else:
                                cols[c] = _object_column(vals)
                        yield BlockAccessor.batch_to_block(cols)
                    else:
                        yield pa.Table.from_pylist(chunk)
                else:
                    yield BlockAccessor.batch_to_block({"item": np.asarray(chunk)})

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=0)))
            start += cnt
        return tasks


class _FileDatasource(Datasource):
    def __init__(self, paths, **read_kwargs):
        self.paths = _expand_paths(paths)
        self.read_kwargs = read_kwargs

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.paths:
            def fn(path=path):
                yield self._read_file(path)

            size = os.path.getsize(path) if os.path.exists(path) else 0
            tasks.append(ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=size, input_files=[path])))
        return tasks


class ParquetDatasource(_FileDatasource):
    def __init__(self, paths, columns: Optional[List[str]] = None, **kw):
        super().__init__(paths, **kw)
        self.columns = columns

    def _read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=self.columns, **self.read_kwargs)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        from pyarrow import csv

        return csv.read_csv(path, **self.read_kwargs)


class JSONDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        from pyarrow import json as pj

        return pj.read_json(path, **self.read_kwargs)


class BinaryDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        return pa.table({"bytes": pa.array([data], type=pa.binary()), "path": [path]})


class TextDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return pa.table({"text": lines})


class ImageDatasource(_FileDatasource):
    """Image files -> rows of {image: HxWxC uint8 tensor, path, height, width}
    (reference _internal/datasource/image_datasource.py). Optional size=(h, w)
    resizes on read; mode forces a PIL conversion (e.g. "RGB", "L")."""

    def __init__(self, paths, size=None, mode: str = "RGB"):
        super().__init__(paths)
        self.size = size
        self.mode = mode

    def _read_file(self, path: str) -> Block:
        from PIL import Image

        with Image.open(path) as im:
            if self.mode:
                im = im.convert(self.mode)
            if self.size is not None:
                im = im.resize((self.size[1], self.size[0]))
            arr = np.asarray(im)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return BlockAccessor.batch_to_block({
            "image": arr[None],  # [1, H, W, C] tensor column
            "path": np.asarray([path]),
            "height": np.asarray([arr.shape[0]]),
            "width": np.asarray([arr.shape[1]]),
        })


class WebDatasetDatasource(_FileDatasource):
    """POSIX-tar shards, one sample per key prefix (reference
    _internal/datasource/webdataset_datasource.py). Members named
    ``<key>.<ext>`` group into one row ``{"__key__": key, ext: decoded, ...}``;
    decoding by extension: jpg/jpeg/png -> HWC uint8 tensor, json -> object,
    cls -> int, txt -> str, npy -> ndarray, anything else -> raw bytes."""

    def __init__(self, paths, decode: bool = True):
        super().__init__(paths)
        self.decode = decode

    def _decode_member(self, ext: str, data: bytes):
        if not self.decode:
            return data
        if ext in ("jpg", "jpeg", "png", "ppm", "bmp"):
            import io

            from PIL import Image

            with Image.open(io.BytesIO(data)) as im:
                return np.asarray(im.convert("RGB"))
        if ext == "json":
            import json

            return json.loads(data)
        if ext == "cls":
            return int(data.decode())
        if ext in ("txt", "text"):
            return data.decode()
        if ext == "npy":
            import io

            return np.load(io.BytesIO(data), allow_pickle=False)
        return data

    def _read_file(self, path: str) -> Block:
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." not in base:
                    key, ext = base, ""
                else:
                    key, ext = base.split(".", 1)
                    ext = ext.lower()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                data = tf.extractfile(member).read()
                # decode by the FINAL extension segment (webdataset convention:
                # "seg.png", "img.npy"); a trailing .npy strips off the column
                # name so ndarray columns round-trip under their own name
                last = ext.rsplit(".", 1)[-1]
                col = ext[: -len(".npy")] if ext.endswith(".npy") and ext != "npy" \
                    else ext
                samples[key][col or "bin"] = self._decode_member(last, data)
        rows = [samples[k] for k in order]
        cols: Dict[str, Any] = {}
        keys = sorted({c for r in rows for c in r})
        for c in keys:
            vals = [r.get(c) for r in rows]
            shapes = {v.shape for v in vals if isinstance(v, np.ndarray)}
            # stack only when EVERY row has this column as a same-shape array;
            # ragged/missing members fall back to an object column
            if vals and len(shapes) == 1 and all(isinstance(v, np.ndarray)
                                                 for v in vals):
                cols[c] = np.stack(vals)
            else:
                cols[c] = _object_column(vals)
        return BlockAccessor.batch_to_block(cols)


class TFRecordDatasource(_FileDatasource):
    """TFRecord files of tf.train.Example protos -> one column per feature
    (reference _internal/datasource/tfrecords_datasource.py). Requires
    tensorflow for the record reader + proto parsing."""

    def _read_file(self, path: str) -> Block:
        try:
            import tensorflow as tf
        except ImportError as e:
            raise ImportError("read_tfrecords requires the 'tensorflow' package") from e
        cols: Dict[str, List[Any]] = {}
        n = 0
        for raw in tf.data.TFRecordDataset(path):
            ex = tf.train.Example()
            ex.ParseFromString(raw.numpy())
            for name, feature in ex.features.feature.items():
                kind = feature.WhichOneof("kind")
                vals = list(getattr(feature, kind).value)
                item = vals[0] if len(vals) == 1 else vals
                cols.setdefault(name, [None] * n).append(item)
            n += 1
            for c in cols.values():
                if len(c) < n:
                    c.append(None)
        return BlockAccessor.batch_to_block(
            {k: np.asarray(v, dtype=object) for k, v in cols.items()})


class LanceDatasource(Datasource):
    """Lance table read (reference _internal/datasource/lance_datasource.py).
    The 'lance' package is optional; absence raises at read time."""

    def __init__(self, uri: str, columns: Optional[List[str]] = None):
        try:
            import lance  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_lance requires the 'lance' package, which is not installed "
                "in this environment") from e
        self.uri = uri
        self.columns = columns

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        import lance

        ds = lance.dataset(self.uri)
        fragments = list(ds.get_fragments())

        def make_fn(frag):
            def fn():
                yield frag.to_table(columns=self.columns)

            return fn

        return [ReadTask(make_fn(f),
                         BlockMetadata(num_rows=-1, size_bytes=0,
                                       input_files=[self.uri]))
                for f in fragments] or [ReadTask(
                    lambda: iter([ds.to_table(columns=self.columns)]),
                    BlockMetadata(num_rows=-1, size_bytes=0, input_files=[self.uri]))]


class BigQueryDatasource(Datasource):
    """BigQuery read via the storage API (reference
    _internal/datasource/bigquery_datasource.py). 'google-cloud-bigquery' is
    optional; absence raises at read time."""

    def __init__(self, project_id: str, dataset: Optional[str] = None,
                 query: Optional[str] = None):
        if bool(dataset) == bool(query):
            raise ValueError("pass exactly one of dataset= or query=")
        try:
            from google.cloud import bigquery  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_bigquery requires the 'google-cloud-bigquery' package, "
                "which is not installed in this environment") from e
        self.project_id = project_id
        self.dataset = dataset
        self.query = query

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        project_id, dataset, query = self.project_id, self.dataset, self.query

        def fn():
            from google.cloud import bigquery as bq

            client = bq.Client(project=project_id)
            if query:
                job = client.query(query)
                yield job.to_arrow()
            else:
                table = client.get_table(dataset)
                yield client.list_rows(table).to_arrow()

        return [ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=0,
                                           input_files=[dataset or "query"]))]


class SQLDatasource(Datasource):
    """Any DBAPI-2 database via a connection factory (reference
    _internal/datasource/sql_datasource.py: read_sql(sql, connection_factory)
    — sqlite3, psycopg2, mysql-connector, ... all satisfy the protocol).
    Unpartitioned single read task, like the reference's default."""

    def __init__(self, sql: str, connection_factory):
        if not callable(connection_factory):
            raise TypeError("connection_factory must be a zero-arg callable "
                            "returning a DBAPI-2 connection")
        self.sql = sql
        self.connection_factory = connection_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self.sql, self.connection_factory

        def fn():
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                cols = [d[0] for d in cur.description]
                rows = cur.fetchall()
                yield BlockAccessor.batch_to_block(
                    {c: np.asarray([r[i] for r in rows])
                     for i, c in enumerate(cols)})
            finally:
                conn.close()

        return [ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=0,
                                           input_files=["sql"]))]


class MongoDatasource(Datasource):
    """MongoDB collection read (reference _internal/datasource/
    mongo_datasource.py). 'pymongo' is optional; absence raises at read time."""

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[List[Dict]] = None):
        try:
            import pymongo  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_mongo requires the 'pymongo' package, which is not "
                "installed in this environment") from e
        self.uri, self.database, self.collection = uri, database, collection
        self.pipeline = pipeline

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri, db, coll, pipeline = (self.uri, self.database, self.collection,
                                   self.pipeline)

        def fn():
            import pymongo

            client = pymongo.MongoClient(uri)
            try:
                c = client[db][coll]
                docs = list(c.aggregate(pipeline) if pipeline else c.find())
                for d in docs:
                    d.pop("_id", None)
                cols = sorted({k for d in docs for k in d})
                yield BlockAccessor.batch_to_block(
                    {k: np.asarray([d.get(k) for d in docs], dtype=object)
                     for k in cols})
            finally:
                client.close()

        return [ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=0,
                                           input_files=[f"{db}.{coll}"]))]


class IcebergDatasource(Datasource):
    """Iceberg table scan (reference _internal/datasource/iceberg_datasource.py).
    'pyiceberg' is optional; absence raises at read time."""

    def __init__(self, table_identifier: str, catalog_kwargs: Optional[Dict] = None,
                 row_filter=None, selected_fields: Optional[List[str]] = None):
        try:
            import pyiceberg  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_iceberg requires the 'pyiceberg' package, which is not "
                "installed in this environment") from e
        self.table_identifier = table_identifier
        self.catalog_kwargs = catalog_kwargs or {}
        self.row_filter = row_filter
        self.selected_fields = selected_fields

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        ident, ckw = self.table_identifier, self.catalog_kwargs
        row_filter, fields = self.row_filter, self.selected_fields

        def fn():
            from pyiceberg.catalog import load_catalog

            table = load_catalog(**ckw).load_table(ident)
            scan_kw = {}
            if row_filter is not None:
                scan_kw["row_filter"] = row_filter
            if fields:
                scan_kw["selected_fields"] = tuple(fields)
            yield table.scan(**scan_kw).to_arrow()

        return [ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=0,
                                           input_files=[ident]))]


class DeltaSharingDatasource(Datasource):
    """Delta Sharing table read (reference _internal/datasource/
    delta_sharing_datasource.py). 'delta-sharing' is optional; absence raises
    at read time."""

    def __init__(self, url: str, limit: Optional[int] = None):
        try:
            import delta_sharing  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_delta_sharing_tables requires the 'delta-sharing' "
                "package, which is not installed in this environment") from e
        self.url = url
        self.limit = limit

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        url, limit = self.url, self.limit

        def fn():
            import delta_sharing

            df = delta_sharing.load_as_pandas(url, limit=limit)
            yield BlockAccessor.batch_to_block(
                {c: df[c].to_numpy() for c in df.columns})

        return [ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=0,
                                           input_files=[url]))]


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(next(iter(self.arrays.values())))
        parallelism = max(1, min(parallelism, n or 1))
        per, rem, start = n // parallelism, n % parallelism, 0
        tasks = []
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            chunk = {k: v[start : start + cnt] for k, v in self.arrays.items()}

            def fn(chunk=chunk):
                yield BlockAccessor.batch_to_block(chunk)

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=sum(v.nbytes for v in chunk.values()))))
            start += cnt
        return tasks


# ---- sinks ------------------------------------------------------------------


class Datasink:
    """Write ABC (reference datasource.py:Datasink). write() runs inside a task."""

    def write(self, block: Block, task_index: int) -> str:
        raise NotImplementedError


class _FileDatasink(Datasink):
    extension = "bin"

    def __init__(self, path: str, filename_prefix: str = "part"):
        self.path = path
        self.filename_prefix = filename_prefix
        os.makedirs(path, exist_ok=True)

    def _target(self, task_index: int) -> str:
        return os.path.join(self.path, f"{self.filename_prefix}-{task_index:06d}.{self.extension}")


class ParquetDatasink(_FileDatasink):
    extension = "parquet"

    def write(self, block: Block, task_index: int) -> str:
        import pyarrow.parquet as pq

        target = self._target(task_index)
        pq.write_table(block, target)
        return target


class CSVDatasink(_FileDatasink):
    extension = "csv"

    def write(self, block: Block, task_index: int) -> str:
        from pyarrow import csv

        target = self._target(task_index)
        csv.write_csv(block, target)
        return target


class JSONDatasink(_FileDatasink):
    extension = "json"

    def write(self, block: Block, task_index: int) -> str:
        import json

        target = self._target(task_index)
        rows = block.to_pylist()
        with open(target, "w") as f:
            for r in rows:
                f.write(json.dumps({k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in r.items()}) + "\n")
        return target


class WebDatasetDatasink(_FileDatasink):
    """One tar shard per write task; rows must carry ``__key__`` plus
    extension-named columns (the read-side contract, round-trippable)."""

    extension = "tar"

    def write(self, block: Block, task_index: int) -> str:
        import io
        import json
        import tarfile

        target = self._target(task_index)
        acc = BlockAccessor.for_block(block)
        with tarfile.open(target, "w") as tf:
            for i, row in enumerate(acc.iter_rows()):
                key = str(row.get("__key__", f"{task_index:06d}{i:06d}"))
                for col, val in row.items():
                    if col == "__key__":
                        continue
                    if isinstance(val, np.ndarray):
                        buf = io.BytesIO()
                        np.save(buf, val)
                        data = buf.getvalue()
                        # "<col>.npy" so the reader both decodes the npy bytes
                        # and restores the original column name
                        name = f"{key}.npy" if col == "npy" else f"{key}.{col}.npy"
                    elif isinstance(val, bytes):
                        data, name = val, f"{key}.{col}"
                    elif isinstance(val, str):
                        data, name = val.encode(), f"{key}.{col}"
                    elif isinstance(val, (int, np.integer)):
                        data, name = str(int(val)).encode(), f"{key}.{col}"
                    else:
                        data, name = json.dumps(val).encode(), f"{key}.{col}"
                    info = tarfile.TarInfo(name=name)
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
        return target


class TFRecordDatasink(_FileDatasink):
    extension = "tfrecords"

    def write(self, block: Block, task_index: int) -> str:
        import tensorflow as tf

        target = self._target(task_index)
        acc = BlockAccessor.for_block(block)
        with tf.io.TFRecordWriter(target) as w:
            for row in acc.iter_rows():
                feats = {}
                for col, val in row.items():
                    if isinstance(val, (bytes, str)):
                        b = val.encode() if isinstance(val, str) else val
                        feats[col] = tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=[b]))
                    elif isinstance(val, (int, np.integer)):
                        feats[col] = tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[int(val)]))
                    elif isinstance(val, (float, np.floating)):
                        feats[col] = tf.train.Feature(
                            float_list=tf.train.FloatList(value=[float(val)]))
                    elif isinstance(val, np.ndarray) and val.dtype.kind in "iu":
                        feats[col] = tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[int(x) for x in val]))
                    elif isinstance(val, np.ndarray) and val.dtype.kind == "f":
                        feats[col] = tf.train.Feature(
                            float_list=tf.train.FloatList(value=[float(x) for x in val]))
                    elif (isinstance(val, (list, tuple)) and val
                          and all(isinstance(x, (int, np.integer)) for x in val)):
                        # the reader returns multi-value features as lists —
                        # round-trips must re-encode them (ADVICE r3)
                        feats[col] = tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[int(x) for x in val]))
                    elif (isinstance(val, (list, tuple)) and val
                          and all(isinstance(x, (float, np.floating)) for x in val)):
                        feats[col] = tf.train.Feature(
                            float_list=tf.train.FloatList(value=[float(x) for x in val]))
                    elif (isinstance(val, (list, tuple)) and val
                          and all(isinstance(x, bytes) for x in val)):
                        feats[col] = tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=list(val)))
                    elif (isinstance(val, np.ndarray) and val.dtype.kind in "OS"
                          and len(val) and all(isinstance(x, bytes) for x in val)):
                        # object-dtype arrays of bytes: block storage turns a
                        # row's list-of-bytes into one of these
                        feats[col] = tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=[bytes(x) for x in val]))
                    else:
                        raise TypeError(
                            f"column {col!r}: cannot encode {type(val).__name__} "
                            "as a tf.train.Feature")
                ex = tf.train.Example(features=tf.train.Features(feature=feats))
                w.write(ex.SerializeToString())
        return target
