"""Datasources and datasinks.

Capability parity: reference python/ray/data/datasource/ + _internal/datasource/
(parquet/csv/json/range/binary read; parquet/csv/json write). A Datasource yields
ReadTasks — serializable thunks each producing one block — which the executor schedules
as ray_tpu tasks.
"""
from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockAccessor, BlockMetadata


@dataclasses.dataclass
class ReadTask:
    """One schedulable unit of reading; fn() -> iterable of Blocks."""

    fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata


class Datasource:
    """ABC (reference datasource.py:Datasource)."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files) if not f.startswith("."))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files found for {paths}")
    return out


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self.n = n
        self.column = column

    def estimate_inmemory_data_size(self):
        return self.n * 8

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        tasks = []
        per = self.n // parallelism
        rem = self.n % parallelism
        start = 0
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            s, e, col = start, start + cnt, self.column

            def fn(s=s, e=e, col=col):
                yield pa.table({col: np.arange(s, e, dtype=np.int64)})

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=cnt * 8)))
            start += cnt
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        tasks = []
        per, rem, start = n // parallelism, n % parallelism, 0
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            chunk = self.items[start : start + cnt]

            def fn(chunk=chunk):
                if chunk and isinstance(chunk[0], dict):
                    yield pa.Table.from_pylist(chunk)
                else:
                    yield BlockAccessor.batch_to_block({"item": np.asarray(chunk)})

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=0)))
            start += cnt
        return tasks


class _FileDatasource(Datasource):
    def __init__(self, paths, **read_kwargs):
        self.paths = _expand_paths(paths)
        self.read_kwargs = read_kwargs

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.paths:
            def fn(path=path):
                yield self._read_file(path)

            size = os.path.getsize(path) if os.path.exists(path) else 0
            tasks.append(ReadTask(fn, BlockMetadata(num_rows=-1, size_bytes=size, input_files=[path])))
        return tasks


class ParquetDatasource(_FileDatasource):
    def __init__(self, paths, columns: Optional[List[str]] = None, **kw):
        super().__init__(paths, **kw)
        self.columns = columns

    def _read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=self.columns, **self.read_kwargs)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        from pyarrow import csv

        return csv.read_csv(path, **self.read_kwargs)


class JSONDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        from pyarrow import json as pj

        return pj.read_json(path, **self.read_kwargs)


class BinaryDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        return pa.table({"bytes": pa.array([data], type=pa.binary()), "path": [path]})


class TextDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return pa.table({"text": lines})


class ImageDatasource(_FileDatasource):
    """Image files -> rows of {image: HxWxC uint8 tensor, path, height, width}
    (reference _internal/datasource/image_datasource.py). Optional size=(h, w)
    resizes on read; mode forces a PIL conversion (e.g. "RGB", "L")."""

    def __init__(self, paths, size=None, mode: str = "RGB"):
        super().__init__(paths)
        self.size = size
        self.mode = mode

    def _read_file(self, path: str) -> Block:
        from PIL import Image

        with Image.open(path) as im:
            if self.mode:
                im = im.convert(self.mode)
            if self.size is not None:
                im = im.resize((self.size[1], self.size[0]))
            arr = np.asarray(im)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return BlockAccessor.batch_to_block({
            "image": arr[None],  # [1, H, W, C] tensor column
            "path": np.asarray([path]),
            "height": np.asarray([arr.shape[0]]),
            "width": np.asarray([arr.shape[1]]),
        })


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(next(iter(self.arrays.values())))
        parallelism = max(1, min(parallelism, n or 1))
        per, rem, start = n // parallelism, n % parallelism, 0
        tasks = []
        for i in range(parallelism):
            cnt = per + (1 if i < rem else 0)
            if cnt == 0:
                continue
            chunk = {k: v[start : start + cnt] for k, v in self.arrays.items()}

            def fn(chunk=chunk):
                yield BlockAccessor.batch_to_block(chunk)

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=cnt, size_bytes=sum(v.nbytes for v in chunk.values()))))
            start += cnt
        return tasks


# ---- sinks ------------------------------------------------------------------


class Datasink:
    """Write ABC (reference datasource.py:Datasink). write() runs inside a task."""

    def write(self, block: Block, task_index: int) -> str:
        raise NotImplementedError


class _FileDatasink(Datasink):
    extension = "bin"

    def __init__(self, path: str, filename_prefix: str = "part"):
        self.path = path
        self.filename_prefix = filename_prefix
        os.makedirs(path, exist_ok=True)

    def _target(self, task_index: int) -> str:
        return os.path.join(self.path, f"{self.filename_prefix}-{task_index:06d}.{self.extension}")


class ParquetDatasink(_FileDatasink):
    extension = "parquet"

    def write(self, block: Block, task_index: int) -> str:
        import pyarrow.parquet as pq

        target = self._target(task_index)
        pq.write_table(block, target)
        return target


class CSVDatasink(_FileDatasink):
    extension = "csv"

    def write(self, block: Block, task_index: int) -> str:
        from pyarrow import csv

        target = self._target(task_index)
        csv.write_csv(block, target)
        return target


class JSONDatasink(_FileDatasink):
    extension = "json"

    def write(self, block: Block, task_index: int) -> str:
        import json

        target = self._target(task_index)
        rows = block.to_pylist()
        with open(target, "w") as f:
            for r in rows:
                f.write(json.dumps({k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in r.items()}) + "\n")
        return target
