"""Aggregations (reference python/ray/data/aggregate.py: AggregateFn, Count/Sum/Min/...)."""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockAccessor


class AggregateFn:
    def __init__(self, on: Optional[str], name: str, fn: Callable[[np.ndarray], float]):
        self.on = on
        self.name = name
        self.fn = fn


class Count(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(on, "count()" if on is None else f"count({on})", lambda a: len(a))


class Sum(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, f"sum({on})", lambda a: np.sum(a))


class Min(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, f"min({on})", lambda a: np.min(a))


class Max(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, f"max({on})", lambda a: np.max(a))


class Mean(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, f"mean({on})", lambda a: float(np.mean(a)))


class Std(AggregateFn):
    def __init__(self, on: str, ddof: int = 1):
        super().__init__(on, f"std({on})", lambda a: float(np.std(a, ddof=ddof)) if len(a) > ddof else 0.0)


class Quantile(AggregateFn):
    def __init__(self, on: str, q: float = 0.5):
        super().__init__(on, f"quantile({on})", lambda a: float(np.quantile(a, q)))


class AbsMax(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, f"abs_max({on})", lambda a: float(np.max(np.abs(a))))


def aggregate_block(block: Block, key: Optional[str], aggs: List[AggregateFn]) -> Block:
    """Apply aggregations to one (hash-partitioned) block, optionally grouped by key."""
    acc = BlockAccessor.for_block(block)
    cols = acc.to_numpy()
    if acc.num_rows() == 0:
        return BlockAccessor.empty()
    if key is None:
        row = {}
        for agg in aggs:
            arr = cols[agg.on] if agg.on else next(iter(cols.values()))
            row[agg.name] = agg.fn(arr)
        return pa.Table.from_pylist([row])
    keys = cols[key]
    uniq = sorted(set(keys.tolist()))
    rows = []
    for k in uniq:
        mask = keys == k
        row = {key: k}
        for agg in aggs:
            arr = cols[agg.on][mask] if agg.on else keys[mask]
            row[agg.name] = agg.fn(arr)
        rows.append(row)
    return pa.Table.from_pylist(rows)
