"""Blocks: the unit of data movement in ray_tpu.data.

Capability parity: reference python/ray/data/block.py (Block/BlockAccessor/BlockMetadata)
and _internal/arrow_block.py / pandas_block.py. A block is a pyarrow.Table travelling
through the object store as an ObjectRef; accessors convert between batch formats.

TPU-first note: the "numpy" batch format (dict[str, np.ndarray]) is the native handoff
into jax.device_put / trainer ingest — columnar, zero-copy via arrow buffers where dtypes allow.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

# A Block is a pyarrow Table. Batches handed to UDFs are format-converted views.
Block = pa.Table
# What UDFs may return / datasources may yield; normalized via BlockAccessor.batch_to_block.
DataBatch = Union[pa.Table, Dict[str, np.ndarray], "pandas.DataFrame", List[dict]]

TENSOR_COLUMN_DTYPE = object  # multi-dim ndarrays are stored as arrow lists per row


@dataclasses.dataclass
class BlockMetadata:
    """Stats carried alongside a block ref (reference block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: Optional[List[str]] = None
    exec_stats: Optional[Dict[str, float]] = None


def _numpy_to_arrow_array(arr: np.ndarray) -> pa.Array:
    if arr.ndim == 1:
        if arr.dtype.kind == "U" or arr.dtype == object:
            # object elements may be ndarrays (ragged tensor column, e.g. a
            # per-row stack of images): arrow only takes nested lists
            return pa.array([x.tolist() if isinstance(x, np.ndarray) else x
                             for x in arr])
        return pa.array(arr)
    # Multi-dim tensor column -> FixedSizeList so round-trips preserve shape.
    inner_len = int(np.prod(arr.shape[1:]))
    flat = pa.array(np.ascontiguousarray(arr).reshape(-1))
    fsl = pa.FixedSizeListArray.from_arrays(flat, inner_len)
    return fsl


class BlockAccessor:
    """Format conversions + row ops over one block (reference BlockAccessor)."""

    def __init__(self, block: pa.Table):
        self._table = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ---- construction -------------------------------------------------------
    @staticmethod
    def batch_to_block(batch: DataBatch, tensor_shapes: Optional[Dict[str, tuple]] = None) -> Block:
        """Normalize any UDF/datasource output into a pyarrow Table."""
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, pa.RecordBatch):
            return pa.Table.from_batches([batch])
        if isinstance(batch, dict):
            cols, names, meta_shapes = [], [], {}
            for name, col in batch.items():
                arr = np.asarray(col) if not isinstance(col, np.ndarray) else col
                if arr.ndim == 0:
                    arr = arr.reshape(1)
                if arr.ndim > 1:
                    meta_shapes[name] = arr.shape[1:]
                cols.append(_numpy_to_arrow_array(arr))
                names.append(name)
            t = pa.Table.from_arrays(cols, names=names)
            if meta_shapes:
                md = dict(t.schema.metadata or {})
                for k, shp in meta_shapes.items():
                    md[f"tensor_shape:{k}".encode()] = repr(tuple(int(s) for s in shp)).encode()
                t = t.replace_schema_metadata(md)
            return t
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:
            pass
        if isinstance(batch, list):  # list of row dicts
            if not batch:
                return pa.table({})
            return pa.Table.from_pylist(batch)
        raise TypeError(f"cannot convert batch of type {type(batch)} to a block")

    @staticmethod
    def empty() -> Block:
        return pa.table({})

    # ---- introspection ------------------------------------------------------
    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def get_metadata(self, input_files: Optional[List[str]] = None, exec_stats=None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files,
            exec_stats=exec_stats,
        )

    def _tensor_shape(self, name: str) -> Optional[tuple]:
        md = self._table.schema.metadata or {}
        raw = md.get(f"tensor_shape:{name}".encode())
        if not raw:
            return None
        import ast

        try:  # literal_eval only — metadata may come from untrusted files
            shape = ast.literal_eval(raw.decode())
        except (ValueError, SyntaxError):
            return None
        return shape if isinstance(shape, tuple) and all(isinstance(s, int) for s in shape) else None

    # ---- batch formats ------------------------------------------------------
    def to_arrow(self) -> pa.Table:
        return self._table

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        out = {}
        for name in columns or self._table.column_names:
            col = self._table.column(name)
            shape = self._tensor_shape(name)
            if shape is not None or pa.types.is_fixed_size_list(col.type) or pa.types.is_list(col.type):
                vals = col.combine_chunks().to_pylist()
                try:
                    arr = np.asarray(vals)
                except ValueError:
                    # ragged list column (rows of differing length / None):
                    # element-wise object fill — np.asarray refuses these
                    arr = np.empty(len(vals), dtype=object)
                    for i, v in enumerate(vals):
                        arr[i] = np.asarray(v) if isinstance(v, list) else v
                if shape is not None and arr.dtype != object:
                    arr = arr.reshape((len(arr),) + tuple(shape))
            else:
                try:
                    arr = col.combine_chunks().to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    arr = np.asarray(col.to_pylist(), dtype=object)
            out[name] = arr
        return out

    def to_pandas(self):
        return self._table.to_pandas()

    def to_batch_format(self, batch_format: Optional[str]):
        if batch_format in (None, "default", "numpy"):
            return self.to_numpy()
        if batch_format == "pyarrow":
            return self.to_arrow()
        if batch_format == "pandas":
            return self.to_pandas()
        raise ValueError(f"unknown batch_format {batch_format!r} (use numpy|pyarrow|pandas)")

    # ---- row ops ------------------------------------------------------------
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        cols = self.to_numpy()
        names = list(cols)
        for i in range(self.num_rows()):
            yield {n: cols[n][i] for n in names}

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices: np.ndarray) -> Block:
        return self._table.take(pa.array(indices))

    def sample(self, n: int, seed: Optional[int] = None) -> Block:
        rng = np.random.default_rng(seed)
        idx = rng.choice(self.num_rows(), size=min(n, self.num_rows()), replace=False)
        return self.take(idx)

    def sort(self, key: str, descending: bool = False) -> Block:
        order = "descending" if descending else "ascending"
        return self._table.sort_by([(key, order)])

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return BlockAccessor.empty()
        try:
            return pa.concat_tables(blocks, promote_options="default")
        except TypeError:
            return pa.concat_tables(blocks)

    def split_by_sizes(self, sizes: List[int]) -> List[Block]:
        out, off = [], 0
        for s in sizes:
            out.append(self.slice(off, off + s))
            off += s
        return out
