"""DataContext: per-driver execution configuration.

Capability parity: reference python/ray/data/context.py:285 (DataContext).
"""
from __future__ import annotations

import dataclasses
from typing import Optional


def _flag(name: str):
    from ray_tpu.config import flag

    return flag(name)


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = dataclasses.field(
        default_factory=lambda: _flag("data_target_max_block_size"))
    target_min_block_size: int = dataclasses.field(
        default_factory=lambda: _flag("data_target_min_block_size"))
    default_batch_size: int = dataclasses.field(
        default_factory=lambda: _flag("data_default_batch_size"))
    read_op_min_num_blocks: int = dataclasses.field(
        default_factory=lambda: _flag("data_read_op_min_num_blocks"))
    # Streaming executor backpressure: max block refs buffered between operators.
    max_inflight_tasks_per_op: int = dataclasses.field(
        default_factory=lambda: _flag("data_max_inflight_tasks_per_op"))
    op_output_buffer_limit: int = dataclasses.field(
        default_factory=lambda: _flag("data_op_output_buffer_limit"))
    actor_pool_min_size: int = 1
    actor_pool_max_size: int = dataclasses.field(
        default_factory=lambda: _flag("data_actor_pool_max_size"))
    # Push-based shuffle (reference push_based_shuffle_task_scheduler.py): maps
    # run in rounds, partitions fold eagerly into per-partition merges —
    # bounded fan-in, map/merge pipelining, early map-output GC. Worth it for
    # large sorts; the pull-based exchange is simpler at test scale.
    use_push_based_shuffle: bool = dataclasses.field(
        default_factory=lambda: _flag("data_push_based_shuffle"))
    push_shuffle_merge_factor: int = dataclasses.field(
        default_factory=lambda: _flag("data_push_shuffle_merge_factor"))
    enable_progress_bars: bool = False
    seed: Optional[int] = None

    _current: "Optional[DataContext]" = None

    @staticmethod
    def get_current() -> "DataContext":
        if DataContext._current is None:
            DataContext._current = DataContext()
        return DataContext._current
