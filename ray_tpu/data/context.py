"""DataContext: per-driver execution configuration.

Capability parity: reference python/ray/data/context.py:285 (DataContext).
"""
from __future__ import annotations

import dataclasses
from typing import Optional


def _flag(name: str):
    from ray_tpu.config import CONFIG

    return getattr(CONFIG, name)


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    default_batch_size: int = 1024
    read_op_min_num_blocks: int = dataclasses.field(
        default_factory=lambda: _flag("data_read_op_min_num_blocks"))
    # Streaming executor backpressure: max block refs buffered between operators.
    max_inflight_tasks_per_op: int = dataclasses.field(
        default_factory=lambda: _flag("data_max_inflight_tasks_per_op"))
    op_output_buffer_limit: int = 16
    actor_pool_min_size: int = 1
    actor_pool_max_size: int = dataclasses.field(
        default_factory=lambda: _flag("data_actor_pool_max_size"))
    use_push_based_shuffle: bool = False
    enable_progress_bars: bool = False
    seed: Optional[int] = None

    _current: "Optional[DataContext]" = None

    @staticmethod
    def get_current() -> "DataContext":
        if DataContext._current is None:
            DataContext._current = DataContext()
        return DataContext._current
