"""DeploymentHandle + router: client-side load balancing and fault tolerance.

Capability parity: reference python/ray/serve/handle.py:639 (DeploymentHandle),
_private/router.py + request_router/pow_2_router.py:27 (power-of-two-choices on
in-flight counts), DeploymentResponse futures. Handles refresh their replica set
from the controller (long-poll analog) and push autoscaling metrics back.

Self-healing additions (reference _private/replica_scheduler backoff +
request retries):
- replica-death/unavailable failures (typed: ActorError / WorkerCrashedError /
  ReplicaUnavailableError / FaultInjectedError) are retried against a
  DIFFERENT replica with bounded exponential backoff; user-code exceptions
  never retry, and deployments declare `retryable=False` to opt out entirely.
- a failure feeds the router's SUSPECT list, so the next pick avoids the dying
  replica before the controller's health check removes it from the long-poll
  view. Streaming calls retry only while no chunk has been yielded.
- handle-side admission control: beyond max_ongoing_requests x replicas +
  max_queued_requests, calls shed with BackPressureError (the proxies turn it
  into 503 + Retry-After) instead of queueing into latency collapse.
- one shared completion waiter per router batches ray_tpu.wait over all
  outstanding requests (one thread, not one per request).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

import ray_tpu
from ray_tpu.core.exceptions import (
    ActorError,
    BackPressureError,
    FaultInjectedError,
    HeadUnavailableError,
    ReplicaUnavailableError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.util import fault_injection, telemetry

from .controller import CONTROLLER_NAME

logger = logging.getLogger("ray_tpu.serve")

_warn_interval_s = 30.0
_last_warn = [0.0]  # monotonic stamp (tracing._maybe_flush convention)


def _throttled_warn(msg: str, *args) -> None:
    now = time.monotonic()
    if now - _last_warn[0] >= _warn_interval_s:
        _last_warn[0] = now
        logger.warning(msg + " (further warnings muted for %.0fs)",
                       *args, _warn_interval_s)


def retry_after_from_latency(latency_s: Optional[float],
                             fallback: float = 1.0) -> float:
    """Shed-hint policy, shared by the handle's BackPressureError and the
    proxies' Retry-After header: ~two recent service times (the queue drains
    one per slot), clamped to a sane wire range."""
    return min(30.0, max(0.5, 2.0 * latency_s)) if latency_s else fallback


def _rid(replica) -> Any:
    """Stable replica identity: the actor id. Long-poll snapshots deliver NEW
    ActorHandle objects for the same replica, so object identity would orphan
    in-flight counts / suspicions on every view change."""
    return replica._actor_id


def is_replica_failure(err: BaseException) -> bool:
    """True when the failure means THE REPLICA (not the request) is bad, so
    resending to a different replica can succeed: actor death, worker crash,
    a draining replica's bounce, or an armed fail point standing in for one.
    User-code exceptions arrive as TaskError and are never retried."""
    if isinstance(err, TaskError):
        return isinstance(err.cause, (FaultInjectedError, ReplicaUnavailableError,
                                      HeadUnavailableError))
    return isinstance(err, (ActorError, WorkerCrashedError,
                            ReplicaUnavailableError, FaultInjectedError,
                            HeadUnavailableError, ConnectionError))


def is_head_unavailable(err: BaseException) -> bool:
    """True when the failure is a HEAD outage, not a replica problem: the
    replica may be perfectly healthy, we just cannot reach it through the
    control plane right now. Retried without consuming the replica budget."""
    if isinstance(err, TaskError):
        return isinstance(err.cause, HeadUnavailableError)
    return isinstance(err, HeadUnavailableError)


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef (reference handle.py).

    result() drives the retry plane: a replica-death classified failure
    resends the request to a different replica (bounded backoff, suspect
    feedback) before surfacing anything to the caller."""

    def __init__(self, ref, session: Optional["_RetrySession"] = None):
        self._ref = ref
        self._session = session

    def result(self, timeout_s: Optional[float] = None) -> Any:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        if self._session is not None:
            # bound the WHOLE retry journey (backoff sleeps, replica
            # re-discovery), not just the get below
            self._session.deadline = deadline
        while True:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                val = ray_tpu.get(self._ref, timeout=remaining)
            except Exception as e:  # noqa: BLE001 — classified below
                if self._session is None:
                    raise
                self._session.prepare_retry(e)  # re-raises when not retryable
                self._ref = self._session.send()
                continue
            if self._session is not None:
                self._session.observe_success()
            return val

    @property
    def ref(self):
        return self._ref

    def __reduce__(self):
        # the retry session holds the handle's router (locks, threads): a
        # serialized response keeps only the ref — retries stay caller-side
        return (DeploymentResponse, (self._ref,))


class DeploymentResponseGenerator:
    """Streaming handle call: iterate replica-yielded values as they arrive
    (reference handle.py DeploymentResponseGenerator over a streaming ObjectRef
    generator). Retries to a different replica ONLY while no chunk has been
    yielded — after first output the stream is observable state the caller may
    have acted on, so mid-stream failures surface."""

    def __init__(self, ref_gen, session: Optional["_RetrySession"] = None):
        self._gen = ref_gen
        self._session = session
        self._yielded = False

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        while True:
            try:
                out = ray_tpu.get(next(self._gen))
            except StopIteration:
                if self._session is not None:
                    self._session.observe_success()  # clean end of stream
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if self._yielded or self._session is None:
                    raise
                self._session.prepare_retry(e)  # re-raises when not retryable
                self._gen = self._session.send()
                continue
            self._yielded = True
            return out

    def close(self) -> None:
        """Abandon the stream: unconsumed items are released and the replica's
        generator is cancelled at its next yield (client-disconnect path)."""
        close = getattr(self._gen, "close", None)
        if close is not None:
            close()

    @property
    def completed(self):
        return self._gen.completed

    def __reduce__(self):
        return (DeploymentResponseGenerator, (self._gen,))


class StreamHandoff:
    """Mid-stream splice of a streaming deployment call across processes.

    A relay deployment (e.g. the P/D router re-streaming a decode replica's
    SSE frames) yields this wrapper as its LAST item to hand the remainder of
    an upstream stream to whoever is draining it — the HTTP proxy — which
    then pulls items straight from the producing replica instead of paying a
    per-item re-put/re-get through the relay process. Construction captures
    the upstream cursor and disowns the relay's generator copy; ``resume()``
    on the receiving side rebuilds an OWNING generator, so exactly one
    process drops unconsumed items on GC/close (a consumer transfer, not a
    broadcast — the relay must stop iterating once it yields this)."""

    def __init__(self, response_gen: "DeploymentResponseGenerator"):
        self._state = response_gen._gen.handoff()

    @classmethod
    def of(cls, stream) -> Optional["StreamHandoff"]:
        """Wrap ``stream`` for handoff, or None when it is not a transferable
        deployment stream (local-testing handles, plain generators) or the
        completion pin could not be taken — the relay then just keeps
        forwarding frames itself, which is always correct."""
        gen = getattr(stream, "_gen", None)
        if isinstance(stream, DeploymentResponseGenerator) and hasattr(
                gen, "handoff"):
            try:
                return cls(stream)
            # graftlint: allow[swallowed-exception] pin failed (head pipe down): fall back to relaying frames in-process rather than hand off a stream the head may free under the adopter
            except Exception:
                return None
        return None

    def resume(self) -> "DeploymentResponseGenerator":
        from ray_tpu.core.object_ref import ObjectRefGenerator

        return DeploymentResponseGenerator(ObjectRefGenerator.adopt(self._state))


class _CompletionWaiter:
    """ONE daemon thread per router batching ray_tpu.wait over every
    outstanding request (was: one thread per request). Callbacks run the
    per-request bookkeeping (router counts, queue-depth gauge, latency
    telemetry) within ~_POLL_S of completion."""

    _POLL_S = 0.05
    _IDLE_RETIRE_S = 30.0
    # consecutive ray_tpu.wait failures before we declare the runtime gone
    # and release ALL bookkeeping — one transient hiccup must not zero the
    # in-flight counts that admission control and p2c read
    _FAIL_FLUSH_THRESHOLD = 3

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._cv = threading.Condition()
        self._pending: Dict[Any, Callable[[], None]] = {}
        self._thread: Optional[threading.Thread] = None

    def add(self, ref, callback: Callable[[], None]) -> None:
        with self._cv:
            self._pending[ref] = callback
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="serve-done-waiter")
                self._thread.start()
            self._cv.notify()

    def outstanding(self) -> int:
        with self._cv:
            return len(self._pending)

    def _loop(self) -> None:
        wait_failures = 0
        while True:
            with self._cv:
                if not self._pending:
                    # park until work arrives; retire ATOMICALLY with the
                    # empty check so repeated run/shutdown cycles don't
                    # accumulate immortal threads
                    if not self._cv.wait(timeout=self._IDLE_RETIRE_S) \
                            and not self._pending:
                        self._thread = None
                        return
                    continue
                refs = list(self._pending.keys())
            fire: List[Callable[[], None]] = []
            try:
                # num_returns=1: wake on the FIRST completion (the store scan
                # returns every ref ready at that moment, not just one), so
                # decrements lag completions by ~1ms instead of a full poll
                # interval — admission control and p2c read near-live counts.
                # The timeout keeps the loop responsive to refs added while
                # this wait was parked on the previous snapshot.
                ready, _ = ray_tpu.wait(refs, num_returns=1,
                                        timeout=self._POLL_S)
                wait_failures = 0
            except Exception as e:  # noqa: BLE001
                wait_failures += 1
                _throttled_warn(
                    "serve completion wait failed for %s/%s (%d outstanding, "
                    "%d consecutive): %r", self.app_name, self.deployment_name,
                    len(refs), wait_failures, e)
                if wait_failures < self._FAIL_FLUSH_THRESHOLD:
                    time.sleep(self._POLL_S)
                    continue
                # runtime durably gone: parity with the old per-request
                # watcher's finally — release the bookkeeping rather than
                # pinning in-flight counts forever
                wait_failures = 0
                ready = refs
            with self._cv:
                for ref in ready:
                    cb = self._pending.pop(ref, None)
                    if cb is not None:
                        fire.append(cb)
            for cb in fire:
                try:
                    cb()
                except Exception as e:  # noqa: BLE001 — bookkeeping must not die
                    _throttled_warn(
                        "serve completion callback failed for %s/%s: %r",
                        self.app_name, self.deployment_name, e)


class _Router:
    """Power-of-two-choices over locally tracked in-flight counts (keyed by
    actor id so counts survive long-poll snapshot churn), with model-affinity
    for multiplexed requests (reference: multiplexed replica ranking in
    request_router) and a suspect list fed by request failures."""

    def __init__(self):
        self.inflight: Dict[Any, int] = {}  # actor id -> in-flight count
        self.model_map: Dict[str, set] = {}  # model_id -> actor ids hosting it
        self.suspects: Dict[Any, float] = {}  # actor id -> suspicion expiry
        self.lock = threading.Lock()
        self.ewma_latency_s = 0.0  # recent request latency (Retry-After input)
        # shared-per-deployment state anchored here because handle.options()
        # clones the handle but reuses the router (all guarded by self.lock)
        self._limits_cache: Optional[tuple] = None  # (expiry, limits dict)
        self._limits_refreshing = False
        self._metrics_thread: Optional[threading.Thread] = None

    # a model-holder this many requests deeper than an alternative loses affinity
    SPILLOVER_THRESHOLD = 2

    def _load(self, replica) -> int:
        return self.inflight.get(_rid(replica), 0)

    def pick(self, replicas: List[Any], model_id: Optional[str] = None,
             exclude: Optional[Set[Any]] = None) -> Any:
        with self.lock:
            now = time.monotonic()
            for rid in [r for r, exp in self.suspects.items() if exp <= now]:
                del self.suspects[rid]
            avoid = set(self.suspects)
            if exclude:
                avoid |= exclude
            live = [r for r in replicas if _rid(r) not in avoid]
            if not live:
                # everything is suspect/excluded: last resort beats no send
                live = [r for r in replicas if _rid(r) not in (exclude or ())]
            if not live:
                live = replicas
            replicas = live
            if model_id:
                ids = {_rid(r): r for r in replicas}
                # holders limited to the pickable view for THIS choice only;
                # the map itself is pruned on long-poll view changes (prune())
                # — a suspect-filtered view must not erase affinity for
                # replicas that are alive and still in the view
                holders = {i for i in self.model_map.get(model_id, ())
                           if i in ids}
                choice = None
                if holders:
                    cid = min(holders, key=lambda i: self.inflight.get(i, 0))
                    choice = ids[cid]
                    others = [r for r in replicas if _rid(r) not in holders]
                    if others:
                        # reference behavior: affinity ranks first but overload
                        # spills to a non-holder (which then loads the model)
                        alt = min(random.sample(others, min(2, len(others))),
                                  key=self._load)
                        if self._load(choice) > self._load(alt) + self.SPILLOVER_THRESHOLD:
                            choice = alt
                if choice is None:
                    choice = (replicas[0] if len(replicas) == 1
                              else min(random.sample(replicas, 2),
                                       key=self._load))
                self.model_map.setdefault(model_id, set()).add(_rid(choice))
                return choice
            if len(replicas) == 1:
                return replicas[0]
            a, b = random.sample(replicas, 2)
            return a if self._load(a) <= self._load(b) else b

    def on_send(self, replica) -> None:
        with self.lock:
            rid = _rid(replica)
            self.inflight[rid] = self.inflight.get(rid, 0) + 1

    def on_done(self, replica) -> None:
        with self.lock:
            rid = _rid(replica)
            if rid in self.inflight:  # pruned replicas must not resurrect
                self.inflight[rid] = max(0, self.inflight[rid] - 1)

    def suspect(self, replica, ttl_s: float) -> None:
        """A request against this replica failed with a replica-death class
        error: stop picking it until the controller's health check catches up
        (or the TTL expires and it proves healthy again)."""
        with self.lock:
            self.suspects[_rid(replica)] = time.monotonic() + ttl_s

    def prune(self, current_ids: Set[Any]) -> None:
        """Drop state for replicas that left the long-poll view (scale-down,
        death): stale entries skew p2c and leak under replica churn."""
        with self.lock:
            for rid in [i for i in self.inflight if i not in current_ids]:
                del self.inflight[rid]
            for rid in [i for i in self.suspects if i not in current_ids]:
                del self.suspects[rid]
            for mid in list(self.model_map):
                kept = {i for i in self.model_map[mid] if i in current_ids}
                if kept:
                    self.model_map[mid] = kept
                else:
                    del self.model_map[mid]

    def observe_latency(self, seconds: float) -> None:
        with self.lock:
            if self.ewma_latency_s == 0.0:
                self.ewma_latency_s = seconds
            else:
                self.ewma_latency_s = 0.8 * self.ewma_latency_s + 0.2 * seconds

    def total_inflight(self) -> int:
        with self.lock:
            return sum(self.inflight.values())


class _LongPollEntry:
    """Shared push-updated replica view for one deployment in this process.

    stale_since stamps the moment the controller became unreachable while a
    view was held: the view is PINNED (kept routable) through the outage —
    degraded-mode serving — and the stamp lets callers report how old the
    routing decision's information is. Cleared on the next successful poll."""

    def __init__(self):
        self.replicas: Optional[List[Any]] = None
        self.stale_since: Optional[float] = None

    def staleness_s(self) -> Optional[float]:
        return None if self.stale_since is None else time.time() - self.stale_since


class _LongPollClient:
    """ONE parked listen_for_change per process, multiplexing every watched
    deployment (reference _private/long_poll.py LongPollClient): however many
    handles and apps exist, each client process costs the controller a single
    concurrency slot."""

    def __init__(self):
        self.lock = threading.Lock()
        self.entries: Dict[tuple, _LongPollEntry] = {}
        self.versions: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _lp_key(key: tuple) -> str:
        return f"replicas::{key[0]}/{key[1]}"

    def watch(self, app_name: str, deployment_name: str) -> _LongPollEntry:
        key = (app_name, deployment_name)
        with self.lock:
            entry = self.entries.get(key)
            if entry is None:
                entry = _LongPollEntry()
                self.entries[key] = entry
                self.versions.setdefault(self._lp_key(key), -1)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="serve-longpoll")
                self._thread.start()
            return entry

    def _loop(self) -> None:
        import os as _os

        from ray_tpu.config import CONFIG as _cfg

        _dbg = _cfg.lp_debug
        errors = 0
        while True:
            with self.lock:
                watched = {self._lp_key(k): self.versions.get(self._lp_key(k), -1)
                           for k in self.entries}
                if _dbg:
                    # warning level: RAY_TPU_LP_DEBUG is an explicit opt-in,
                    # and nothing configures logging, so info() would vanish
                    logger.warning("[lp] watched=%s", watched)
                if not watched:
                    # retire ATOMICALLY with the empty check: a concurrent watch()
                    # either sees entries (we keep looping) or sees _thread=None
                    # and respawns — never a live-looking thread about to exit
                    self._thread = None
                    return
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                res = ray_tpu.get(controller.listen_for_change.remote(watched, 10.0))
                errors = 0
            except Exception as lp_err:
                with self.lock:
                    stamp = time.time()
                    for e in self.entries.values():
                        # PIN the last-known view through the outage instead
                        # of dropping it: requests keep routing to the
                        # replicas we knew about (replica death during the
                        # window is absorbed by the suspect/retry plane),
                        # stamped so staleness is observable
                        if e.replicas is not None and e.stale_since is None:
                            e.stale_since = stamp
                errors += 1
                if errors == 1:
                    # one line per outage, not one per second of it
                    logger.warning("serve long-poll watch failed (%r); "
                                   "pinning the last replica view while "
                                   "retrying", lp_err)
                if errors > 30:
                    # controller gone for ~30s: retire; a later watch() respawns
                    with self.lock:
                        self._thread = None
                    return
                time.sleep(1.0)
                continue
            if _dbg:
                logger.warning("[lp] res=%s", {
                    k: (v, s if s is None else len(s))
                    for k, (v, s) in res.items()})
            with self.lock:
                for lp_key, (version, snapshot) in res.items():
                    self.versions[lp_key] = version
                    tup = tuple(lp_key.split("::", 1)[1].split("/", 1))
                    entry = self.entries.get(tup)
                    if entry is None:
                        continue
                    if snapshot is None:  # deployment deleted: stop watching it
                        entry.replicas = None
                        entry.stale_since = None
                        del self.entries[tup]
                        self.versions.pop(lp_key, None)
                    else:
                        entry.replicas = snapshot
                        entry.stale_since = None  # fresh view: outage over


# process-wide in-flight accounting behind the serve_queue_depth gauge
_inflight_lock = threading.Lock()
_inflight_by_dep: Dict[tuple, int] = {}

_long_poll_client = _LongPollClient()
_lp_registry = _long_poll_client.entries  # introspection/tests


def _ensure_long_poll(app_name: str, deployment_name: str) -> _LongPollEntry:
    return _long_poll_client.watch(app_name, deployment_name)


def _reset_long_poll() -> None:
    """Forget all watches (serve.shutdown): they reference a dead controller,
    and a fresh controller restarts its version counters from zero."""
    with _long_poll_client.lock:
        _long_poll_client.entries.clear()
        _long_poll_client.versions.clear()


class _RetrySession:
    """One logical request's journey across replicas. Owns the retry budget,
    the per-replica exclusion set, and the backoff schedule; DeploymentResponse
    / DeploymentResponseGenerator call prepare_retry() + send() when an attempt
    fails with a replica-death class error."""

    def __init__(self, handle: "DeploymentHandle", args: tuple, kwargs: dict,
                 retryable: bool, trace_id: Optional[str]):
        from ray_tpu.config import CONFIG

        self.handle = handle
        self.args = args
        self.kwargs = kwargs
        self.trace_id = trace_id
        self.attempts_left = CONFIG.serve_request_retries if retryable else 0
        self.backoff_s = CONFIG.serve_retry_backoff_s
        self.backoff_max_s = CONFIG.serve_retry_backoff_max_s
        self.suspect_ttl_s = CONFIG.serve_suspect_ttl_s
        self.exclude: Set[Any] = set()  # actor ids already tried and failed
        self.dead_ids: Set[Any] = set()  # subset seen die AUTHORITATIVELY
        self.replica = None  # replica of the LAST attempt
        self.attempt = 0
        self.deadline: Optional[float] = None  # caller's result(timeout_s) bound
        self.head_deadline: Optional[float] = None  # armed on first head outage
        self.t0_perf = 0  # send time of the last attempt (perf_counter_ns)
        self.completed_dur_ns: Optional[int] = None  # stamped by the waiter
        self._observed = False  # EWMA fed at most once per logical request

    def prepare_retry(self, err: BaseException) -> None:
        """Classify a failed attempt; re-raise when the request must surface
        (user error, budget exhausted, retryable=False, caller deadline
        passed), otherwise mark the replica suspect and sleep the backoff."""
        if is_head_unavailable(err):
            # a head outage is not the replica's fault: retry WITHOUT spending
            # the replica budget or suspecting anyone, bounded by its own
            # window (the reconnect horizon plus restart slack) so a head
            # that never comes back still surfaces the typed error
            from ray_tpu.config import CONFIG
            if self.head_deadline is None:
                self.head_deadline = (time.monotonic()
                                      + CONFIG.head_reconnect_timeout_s + 10.0)
            if time.monotonic() >= self.head_deadline:
                raise err
            if self.deadline is not None and time.monotonic() >= self.deadline:
                raise err
            self.attempt += 1
            delay = min(self.backoff_s * (2 ** (self.attempt - 1)),
                        self.backoff_max_s)
            delay *= 0.5 + random.random() * 0.5
            if self.deadline is not None:
                delay = min(delay, max(0.0, self.deadline - time.monotonic()))
            time.sleep(delay)
            return
        if not is_replica_failure(err) or self.attempts_left <= 0:
            raise err
        if self.deadline is not None and time.monotonic() >= self.deadline:
            raise err  # the caller's timeout outranks the retry budget
        self.attempts_left -= 1
        self.attempt += 1
        # ActorDiedError/WorkerCrashedError come from the cluster's own death
        # detection — authoritative, unlike an injected or draining bounce
        authoritative = isinstance(err, (ActorError, WorkerCrashedError))
        if self.replica is not None:
            self.handle._router.suspect(self.replica, self.suspect_ttl_s)
            self.exclude.add(_rid(self.replica))
            if authoritative:
                self.dead_ids.add(_rid(self.replica))
                # push the death to the controller: the replica must leave the
                # routing view NOW, not a health_check_period_s later — the
                # window where a scale-down could drain the healthy replicas
                # and keep this dead one
                try:
                    self.handle._controller().report_replica_failure.remote(
                        self.handle.app_name, self.handle.deployment_name,
                        _rid(self.replica))
                # graftlint: allow[swallowed-exception] best-effort death report; the controller's own health check converges anyway
                except Exception:  # noqa: BLE001 — best-effort push
                    pass
        logger.info(
            "serve request to %s/%s failed on replica (attempt %d, %s); "
            "retrying on a different replica",
            self.handle.app_name, self.handle.deployment_name, self.attempt,
            type(err.cause if isinstance(err, TaskError) else err).__name__)
        # bounded exponential backoff with jitter (decorrelates retry storms)
        delay = min(self.backoff_s * (2 ** (self.attempt - 1)), self.backoff_max_s)
        delay *= 0.5 + random.random() * 0.5
        if self.deadline is not None:
            delay = min(delay, max(0.0, self.deadline - time.monotonic()))
        time.sleep(delay)
        if authoritative:
            # a retry against a KNOWN-dead replica is a wasted attempt: wait
            # (bounded) for the reported death to propagate into a view that
            # offers something else before spending the next one
            self.handle._await_non_dead_replica(self.dead_ids, self.deadline)

    def send(self):
        """One attempt: pick (excluding failed replicas), send, register with
        the completion waiter. Synchronous send failures consume retry budget
        here instead of surfacing half-initialized responses."""
        while True:
            try:
                return self.handle._send_once(self)
            except Exception as e:  # noqa: BLE001 — classified by prepare_retry
                self.prepare_retry(e)

    def observe_success(self) -> None:
        """Feed the router's Retry-After EWMA from a request that SUCCEEDED:
        fast-error completions (drain bounces, dead replicas, fail points)
        must not collapse the shed hint exactly when callers should back off.
        Uses the waiter's true completion duration when it has fired, else
        send→now (the get that just returned makes them ~equal)."""
        if self._observed:
            return
        self._observed = True
        dur = self.completed_dur_ns
        if dur is None:
            dur = time.perf_counter_ns() - self.t0_perf
        try:
            self.handle._router.observe_latency(dur / 1e9)
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the data path down
        except Exception:  # noqa: BLE001 — load signals must never fail a request
            pass


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        self._router = _Router()
        self._waiter = _CompletionWaiter(app_name, deployment_name)
        self._replicas: List[Any] = []
        self._last_refresh = 0.0
        self._refresh_interval = 1.0
        self._last_view: Optional[List[Any]] = None  # router-prune change detector

    # -- plumbing --------------------------------------------------------------
    def _controller(self):
        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False) -> None:
        # push path: the shared long-poll listener keeps this view current
        entry = _lp_registry.get((self.app_name, self.deployment_name))
        if entry is not None and entry.replicas is not None and not force:
            self._replicas = entry.replicas
            self._maybe_prune(entry.replicas)
            return
        now = time.time()
        if not force and now - self._last_refresh < self._refresh_interval and self._replicas:
            return
        try:
            replicas = ray_tpu.get(
                self._controller().get_replicas.remote(self.app_name, self.deployment_name)
            )
        except Exception:
            # controller/head unreachable: degraded mode keeps serving from
            # the last-known replica set (dead replicas are absorbed by the
            # retry plane); only a handle with NO view at all surfaces this
            if self._replicas:
                self._last_refresh = now  # don't hammer a dead controller
                return
            raise
        self._replicas = replicas
        self._maybe_prune(replicas)
        self._last_refresh = now

    def _maybe_prune(self, view: List[Any]) -> None:
        """On a replica-set change, drop router state for departed replicas
        (the controller-side health/drain push arrives as exactly this view
        change). Identity check keeps the per-call cost at one comparison."""
        if view is self._last_view:
            return
        self._last_view = view
        self._router.prune({_rid(r) for r in view})

    def _fetch_limits(self, now: float) -> None:
        """Blocking fetch + cache fill (runs on the caller only when no value
        exists yet; otherwise on a background refresh thread). When the
        controller is unreachable the fallback FAILS SAFE: retryable=False —
        re-executing a non-idempotent method is worse than surfacing one
        error — cached only briefly so recovery is quick."""
        from ray_tpu.config import CONFIG

        limits = None
        try:
            limits = ray_tpu.get(self._controller().get_deployment_limits.remote(
                self.app_name, self.deployment_name), timeout=5)
        except Exception as e:  # noqa: BLE001 — controller busy/gone
            logger.debug("deployment-limits fetch failed (%r); keeping the "
                         "cached admission limits", e)
        ttl = 30.0
        if limits is None:
            ttl = 5.0
            limits = {"max_ongoing_requests": CONFIG.serve_max_ongoing_requests,
                      "max_queued_requests": CONFIG.serve_max_queued_requests,
                      "retryable": False}
        with self._router.lock:
            self._router._limits_cache = (now + ttl, limits)
            self._router._limits_refreshing = False

    def _limits(self) -> Dict[str, Any]:
        """Deployment admission/retry knobs, cached on the shared router (30s
        TTL), STALE-WHILE-REVALIDATE: an expired value is served immediately
        while one background thread refreshes it, so the request hot path
        never blocks on a busy controller after the first call."""
        now = time.monotonic()
        with self._router.lock:
            cached = self._router._limits_cache
            if cached is not None:
                if cached[0] <= now and not self._router._limits_refreshing:
                    self._router._limits_refreshing = True
                    threading.Thread(target=self._fetch_limits, args=(now,),
                                     daemon=True,
                                     name="serve-limits-refresh").start()
                return cached[1]
        self._fetch_limits(now)  # first call: nothing to serve stale
        with self._router.lock:
            return self._router._limits_cache[1]

    def _ensure_metrics_push(self) -> None:
        # anchored on the shared router under its lock: options() clones and
        # concurrent first-callers reuse one pusher
        with self._router.lock:
            t = self._router._metrics_thread
            if t is not None and t.is_alive():
                return
            router = self._router
            app, dep = self.app_name, self.deployment_name

            def push():
                # daemon thread keyed to the router's lifetime; exits once the
                # controller has been gone for a while (serve.shutdown) so
                # repeated run/shutdown cycles don't accumulate immortal threads
                errors = 0
                while errors < 30:
                    try:
                        ray_tpu.get_actor(CONTROLLER_NAME).record_handle_metrics.remote(
                            app, dep, float(router.total_inflight()))
                        errors = 0
                    # graftlint: allow[swallowed-exception] failure is counted; the push loop retries every second and retires after 30
                    except Exception:
                        errors += 1
                    time.sleep(1.0)

            router._metrics_thread = threading.Thread(
                target=push, daemon=True, name="serve-router-metrics")
            router._metrics_thread.start()

    def _adjust_queue_depth(self, delta: int) -> None:
        """Live load signal for routing/autoscaling and `ray-tpu status`.

        Accounting is PROCESS-wide per deployment (not per router): several
        handles to one deployment in one process would otherwise last-write
        each other's gauge. The `proc` tag keeps each process's value distinct
        through the gauge merge (which is last-write per tag set), so
        cluster_status can SUM them into the true cluster-wide depth."""
        key = (self.app_name, self.deployment_name)
        with _inflight_lock:
            n = max(0, _inflight_by_dep.get(key, 0) + delta)
            _inflight_by_dep[key] = n
        try:
            import os as _os

            telemetry.get_gauge(
                "serve_queue_depth",
                "in-flight handle requests (per deployment, per process)",
                tag_keys=("app", "deployment", "proc")).set(
                float(n), tags={"app": self.app_name,
                                "deployment": self.deployment_name,
                                "proc": str(_os.getpid())})
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the data path down
        except Exception:
            pass  # load signals must never fail a request

    def retry_after_hint_s(self) -> float:
        """How long a shed caller should wait before retrying, from the
        router's recent-latency EWMA. The proxies refine this with the head's
        windowed latency history (same clamp policy, shared helper)."""
        return retry_after_from_latency(self._router.ewma_latency_s or None)

    def _maybe_shed(self, limits: Dict[str, Any]) -> None:
        """Handle-side load shedding: past replica capacity plus the queue
        allowance, fail FAST with a typed, Retry-After-carrying error instead
        of stacking latency. Accounting is per-process (each proxy/driver
        sheds on its own view), matching the queue-depth gauge's scope."""
        max_queued = limits.get("max_queued_requests", -1)
        if max_queued is None or max_queued < 0:
            return
        moq = max(1, limits.get("max_ongoing_requests", 1) or 1)
        # target-aware: while a controller scale-up is young, size admission
        # on the anticipated replica count so the queue builds for capacity
        # that is arriving instead of shedding through the whole ramp; a
        # scale-up that never becomes healthy expires the anticipation
        # controller-side and shedding resumes (the autoscaler's "re-shed")
        anticipated = int(limits.get("anticipated_replicas") or 0)
        capacity = moq * max(1, len(self._replicas), anticipated)
        # PROCESS-wide depth (the queue-depth gauge's accounting), not this
        # router's: several handles to one deployment must share one limit
        with _inflight_lock:
            depth = _inflight_by_dep.get(
                (self.app_name, self.deployment_name), 0)
        if depth < capacity + max_queued:
            return
        try:
            telemetry.get_counter(
                "serve_requests_shed_total",
                "handle calls rejected by admission control",
                tag_keys=("app", "deployment")).inc(
                tags={"app": self.app_name,
                      "deployment": self.deployment_name})
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the data path down
        except Exception:
            pass  # shedding must not depend on telemetry
        raise BackPressureError(self.app_name, self.deployment_name,
                                queue_depth=depth,
                                limit=capacity + max_queued,
                                retry_after_s=self.retry_after_hint_s())

    # -- public ----------------------------------------------------------------
    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None, **_compat) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.app_name, self.deployment_name, method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            self._stream if stream is None else stream,
        )
        h._router = self._router  # share in-flight + model-affinity view
        h._waiter = self._waiter  # and the batched completion waiter
        h._replicas = self._replicas
        h._last_refresh = self._last_refresh
        h._last_view = self._last_view
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def _wait_for_replicas(self, deadline: Optional[float] = None) -> None:
        from ray_tpu.config import CONFIG

        cap = time.monotonic() + CONFIG.serve_replica_wait_s
        if deadline is not None:
            cap = min(cap, deadline)  # the caller's result() timeout wins
        while True:
            self._refresh()
            if self._replicas:
                return
            if time.monotonic() > cap:
                raise RuntimeError(
                    f"no running replicas for {self.app_name}/{self.deployment_name}"
                )
            time.sleep(0.1)
            self._last_refresh = 0.0  # force re-poll

    def _await_non_dead_replica(self, dead_ids: Set[Any],
                                deadline: Optional[float],
                                cap_s: float = 10.0) -> None:
        """Block (bounded) until the view offers a replica NOT known dead —
        the reconcile loop needs a tick or two to replace a reported death,
        and spending retry budget on the corpse meanwhile guarantees failure."""
        cap = time.monotonic() + cap_s
        if deadline is not None:
            cap = min(cap, deadline)
        while time.monotonic() < cap:
            try:
                self._refresh(force=True)
            # graftlint: allow[swallowed-exception] controller briefly unreachable; the wait loop keeps polling until its deadline
            except Exception:  # noqa: BLE001 — controller briefly unreachable
                pass
            if any(_rid(r) not in dead_ids for r in self._replicas):
                return
            time.sleep(0.15)

    def _send_once(self, session: _RetrySession):
        """One attempt: pick a replica (suspects + the session's failed set
        excluded), send, and register completion bookkeeping with the shared
        waiter. Returns the raw ref (or streaming ref generator)."""
        self._wait_for_replicas(deadline=session.deadline)
        replica = self._router.pick(self._replicas,
                                    self._multiplexed_model_id or None,
                                    exclude=session.exclude)
        session.replica = replica  # before the try: a send-time failure must
        # suspect the replica it was aimed at, not the previous attempt's
        self._router.on_send(replica)
        self._adjust_queue_depth(+1)
        t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
        try:
            fault_injection.fail_point(
                "serve.handle.send", app=self.app_name,
                deployment=self.deployment_name, attempt=session.attempt)
            method = replica.handle_request
            if self._stream:
                # replica yields; items stream through the object store as they
                # are produced (core num_returns="streaming" generators)
                method = method.options(num_returns="streaming")
            ref = method.remote(self._method, session.args, session.kwargs)
        except BaseException:
            self._router.on_done(replica)
            self._adjust_queue_depth(-1)  # the send never happened
            raise
        done_ref = ref.completed if self._stream else ref
        router, waiter = self._router, self._waiter
        app, dep, meth, stream = (self.app_name, self.deployment_name,
                                  self._method, self._stream)
        trace_id = session.trace_id
        session.t0_perf = t0_perf
        session.completed_dur_ns = None
        my_attempt = session.attempt

        def on_complete():
            router.on_done(replica)
            self._adjust_queue_depth(-1)
            dur = time.perf_counter_ns() - t0_perf
            if session.attempt == my_attempt:
                # true completion duration for observe_success (the EWMA feed
                # happens there, on KNOWN success — not here, where a fast
                # error completion is indistinguishable from a fast request)
                session.completed_dur_ns = dur
            telemetry.get_histogram(
                "serve_request_seconds",
                "handle-call latency (send to completion)",
                tag_keys=("app", "deployment")).observe(
                dur / 1e9, tags={"app": app, "deployment": dep})
            if telemetry.enabled():
                telemetry.complete(
                    "serve.request", "serve", t0_wall, dur,
                    app=app, deployment=dep, method=meth, stream=stream,
                    trace_id=trace_id)

        waiter.add(done_ref, on_complete)
        return ref

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._ensure_metrics_push()
        _ensure_long_poll(self.app_name, self.deployment_name)
        fault_injection.fail_point(
            "serve.handle.request", app=self.app_name,
            deployment=self.deployment_name)
        self._wait_for_replicas()
        limits = self._limits()
        self._maybe_shed(limits)
        # captured HERE, on the caller's thread: the completion-waiter thread
        # that records the lifecycle event has no request context of its own
        try:
            from ray_tpu.util.tracing import current_trace_id

            trace_id = current_trace_id()
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (trace_id = None) by design
        except Exception:
            trace_id = None
        if self._multiplexed_model_id:
            from .multiplex import MULTIPLEX_KWARG

            kwargs = {**kwargs, MULTIPLEX_KWARG: self._multiplexed_model_id}
        session = _RetrySession(self, args, kwargs,
                                retryable=bool(limits.get("retryable", True)),
                                trace_id=trace_id)
        ref = session.send()
        if self._stream:
            return DeploymentResponseGenerator(ref, session)
        return DeploymentResponse(ref, session)
