"""DeploymentHandle + router: client-side load balancing.

Capability parity: reference python/ray/serve/handle.py:639 (DeploymentHandle),
_private/router.py + request_router/pow_2_router.py:27 (power-of-two-choices on
in-flight counts), DeploymentResponse futures. Handles refresh their replica set from
the controller (long-poll analog) and push autoscaling metrics back.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.util import telemetry

from .controller import CONTROLLER_NAME


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef (reference handle.py)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref) if timeout_s is None else ray_tpu.get(self._ref)

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming handle call: iterate replica-yielded values as they arrive
    (reference handle.py DeploymentResponseGenerator over a streaming ObjectRef
    generator)."""

    def __init__(self, ref_gen):
        self._gen = ref_gen

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        return ray_tpu.get(next(self._gen))

    def close(self) -> None:
        """Abandon the stream: unconsumed items are released and the replica's
        generator is cancelled at its next yield (client-disconnect path)."""
        close = getattr(self._gen, "close", None)
        if close is not None:
            close()

    @property
    def completed(self):
        return self._gen.completed


class _Router:
    """Power-of-two-choices over locally tracked in-flight counts, with
    model-affinity for multiplexed requests (reference: multiplexed replica
    ranking in request_router)."""

    def __init__(self):
        self.inflight: Dict[Any, int] = {}
        self.model_map: Dict[str, set] = {}  # model_id -> replicas observed hosting it
        self.lock = threading.Lock()

    # a model-holder this many requests deeper than an alternative loses affinity
    SPILLOVER_THRESHOLD = 2

    def pick(self, replicas: List[Any], model_id: Optional[str] = None) -> Any:
        with self.lock:
            if model_id:
                live = {r for r in self.model_map.get(model_id, ()) if r in replicas}
                self.model_map[model_id] = live  # prune dead replicas
                choice = None
                if live:
                    choice = min(live, key=lambda r: self.inflight.get(r, 0))
                    others = [r for r in replicas if r not in live]
                    if others:
                        # reference behavior: affinity ranks first but overload
                        # spills to a non-holder (which then loads the model)
                        alt = min(random.sample(others, min(2, len(others))),
                                  key=lambda r: self.inflight.get(r, 0))
                        if (self.inflight.get(choice, 0)
                                > self.inflight.get(alt, 0) + self.SPILLOVER_THRESHOLD):
                            choice = alt
                if choice is None:
                    choice = (replicas[0] if len(replicas) == 1
                              else min(random.sample(replicas, 2),
                                       key=lambda r: self.inflight.get(r, 0)))
                self.model_map[model_id].add(choice)
                return choice
            if len(replicas) == 1:
                return replicas[0]
            a, b = random.sample(replicas, 2)
            return a if self.inflight.get(a, 0) <= self.inflight.get(b, 0) else b

    def on_send(self, replica) -> None:
        with self.lock:
            self.inflight[replica] = self.inflight.get(replica, 0) + 1

    def on_done(self, replica) -> None:
        with self.lock:
            self.inflight[replica] = max(0, self.inflight.get(replica, 0) - 1)

    def total_inflight(self) -> int:
        with self.lock:
            return sum(self.inflight.values())


class _LongPollEntry:
    """Shared push-updated replica view for one deployment in this process."""

    def __init__(self):
        self.replicas: Optional[List[Any]] = None


class _LongPollClient:
    """ONE parked listen_for_change per process, multiplexing every watched
    deployment (reference _private/long_poll.py LongPollClient): however many
    handles and apps exist, each client process costs the controller a single
    concurrency slot."""

    def __init__(self):
        self.lock = threading.Lock()
        self.entries: Dict[tuple, _LongPollEntry] = {}
        self.versions: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _lp_key(key: tuple) -> str:
        return f"replicas::{key[0]}/{key[1]}"

    def watch(self, app_name: str, deployment_name: str) -> _LongPollEntry:
        key = (app_name, deployment_name)
        with self.lock:
            entry = self.entries.get(key)
            if entry is None:
                entry = _LongPollEntry()
                self.entries[key] = entry
                self.versions.setdefault(self._lp_key(key), -1)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="serve-longpoll")
                self._thread.start()
            return entry

    def _loop(self) -> None:
        import os as _os

        from ray_tpu.config import CONFIG as _cfg

        _dbg = _cfg.lp_debug
        errors = 0
        while True:
            with self.lock:
                watched = {self._lp_key(k): self.versions.get(self._lp_key(k), -1)
                           for k in self.entries}
                if _dbg:
                    print(f"[lp] watched={watched}", flush=True)
                if not watched:
                    # retire ATOMICALLY with the empty check: a concurrent watch()
                    # either sees entries (we keep looping) or sees _thread=None
                    # and respawns — never a live-looking thread about to exit
                    self._thread = None
                    return
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                res = ray_tpu.get(controller.listen_for_change.remote(watched, 10.0))
                errors = 0
            except Exception:
                with self.lock:
                    for e in self.entries.values():
                        e.replicas = None  # fall back to interval polling
                errors += 1
                if errors > 30:
                    # controller gone for ~30s: retire; a later watch() respawns
                    with self.lock:
                        self._thread = None
                    return
                time.sleep(1.0)
                continue
            if _dbg:
                print(f"[lp] res={ {k: (v, s if s is None else len(s)) for k, (v, s) in res.items()} }", flush=True)
            with self.lock:
                for lp_key, (version, snapshot) in res.items():
                    self.versions[lp_key] = version
                    tup = tuple(lp_key.split("::", 1)[1].split("/", 1))
                    entry = self.entries.get(tup)
                    if entry is None:
                        continue
                    if snapshot is None:  # deployment deleted: stop watching it
                        entry.replicas = None
                        del self.entries[tup]
                        self.versions.pop(lp_key, None)
                    else:
                        entry.replicas = snapshot


# process-wide in-flight accounting behind the serve_queue_depth gauge
_inflight_lock = threading.Lock()
_inflight_by_dep: Dict[tuple, int] = {}

_long_poll_client = _LongPollClient()
_lp_registry = _long_poll_client.entries  # introspection/tests


def _ensure_long_poll(app_name: str, deployment_name: str) -> _LongPollEntry:
    return _long_poll_client.watch(app_name, deployment_name)


def _reset_long_poll() -> None:
    """Forget all watches (serve.shutdown): they reference a dead controller,
    and a fresh controller restarts its version counters from zero."""
    with _long_poll_client.lock:
        _long_poll_client.entries.clear()
        _long_poll_client.versions.clear()


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        self._router = _Router()
        self._replicas: List[Any] = []
        self._last_refresh = 0.0
        self._refresh_interval = 1.0

    # -- plumbing --------------------------------------------------------------
    def _controller(self):
        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False) -> None:
        # push path: the shared long-poll listener keeps this view current
        entry = _lp_registry.get((self.app_name, self.deployment_name))
        if entry is not None and entry.replicas is not None and not force:
            self._replicas = entry.replicas
            return
        now = time.time()
        if not force and now - self._last_refresh < self._refresh_interval and self._replicas:
            return
        replicas = ray_tpu.get(
            self._controller().get_replicas.remote(self.app_name, self.deployment_name)
        )
        self._replicas = replicas
        self._last_refresh = now

    def _ensure_metrics_push(self) -> None:
        # anchored on the shared router under its lock: options() clones and
        # concurrent first-callers reuse one pusher
        with self._router.lock:
            t = getattr(self._router, "_metrics_thread", None)
            if t is not None and t.is_alive():
                return
            router = self._router
            app, dep = self.app_name, self.deployment_name

            def push():
                # daemon thread keyed to the router's lifetime; exits once the
                # controller has been gone for a while (serve.shutdown) so
                # repeated run/shutdown cycles don't accumulate immortal threads
                errors = 0
                while errors < 30:
                    try:
                        ray_tpu.get_actor(CONTROLLER_NAME).record_handle_metrics.remote(
                            app, dep, float(router.total_inflight()))
                        errors = 0
                    except Exception:
                        errors += 1
                    time.sleep(1.0)

            router._metrics_thread = threading.Thread(target=push, daemon=True)
            router._metrics_thread.start()

    def _adjust_queue_depth(self, delta: int) -> None:
        """Live load signal for routing/autoscaling and `ray-tpu status`.

        Accounting is PROCESS-wide per deployment (not per router): several
        handles to one deployment in one process would otherwise last-write
        each other's gauge. The `proc` tag keeps each process's value distinct
        through the gauge merge (which is last-write per tag set), so
        cluster_status can SUM them into the true cluster-wide depth."""
        key = (self.app_name, self.deployment_name)
        with _inflight_lock:
            n = max(0, _inflight_by_dep.get(key, 0) + delta)
            _inflight_by_dep[key] = n
        try:
            import os as _os

            telemetry.get_gauge(
                "serve_queue_depth",
                "in-flight handle requests (per deployment, per process)",
                tag_keys=("app", "deployment", "proc")).set(
                float(n), tags={"app": self.app_name,
                                "deployment": self.deployment_name,
                                "proc": str(_os.getpid())})
        except Exception:
            pass  # load signals must never fail a request

    # -- public ----------------------------------------------------------------
    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None, **_compat) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.app_name, self.deployment_name, method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            self._stream if stream is None else stream,
        )
        h._router = self._router  # share in-flight + model-affinity view
        h._replicas = self._replicas
        h._last_refresh = self._last_refresh
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._ensure_metrics_push()
        _ensure_long_poll(self.app_name, self.deployment_name)
        from ray_tpu.config import CONFIG

        deadline = time.time() + CONFIG.serve_replica_wait_s
        while True:
            self._refresh()
            if self._replicas:
                break
            if time.time() > deadline:
                raise RuntimeError(
                    f"no running replicas for {self.app_name}/{self.deployment_name}"
                )
            time.sleep(0.1)
            self._last_refresh = 0.0  # force re-poll
        replica = self._router.pick(self._replicas, self._multiplexed_model_id or None)
        self._router.on_send(replica)
        self._adjust_queue_depth(+1)
        t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
        # captured HERE, on the caller's thread: the done-watcher thread that
        # records the lifecycle event has no request context of its own
        try:
            from ray_tpu.util.tracing import current_trace_id

            trace_id = current_trace_id()
        except Exception:
            trace_id = None
        if self._multiplexed_model_id:
            from .multiplex import MULTIPLEX_KWARG

            kwargs = {**kwargs, MULTIPLEX_KWARG: self._multiplexed_model_id}
        try:
            method = replica.handle_request
            if self._stream:
                # replica yields; items stream through the object store as they
                # are produced (core num_returns="streaming" generators)
                method = method.options(num_returns="streaming")
            ref = method.remote(self._method, args, kwargs)
        except Exception:
            self._router.on_done(replica)
            self._adjust_queue_depth(-1)  # the send never happened
            raise

        done_ref = ref.completed if self._stream else ref
        resp = (DeploymentResponseGenerator(ref) if self._stream
                else DeploymentResponse(ref))

        def _done_watcher():
            try:
                ray_tpu.wait([done_ref], num_returns=1, timeout=None)
            except Exception:
                pass
            finally:
                self._router.on_done(replica)
                self._adjust_queue_depth(-1)
                dur = time.perf_counter_ns() - t0_perf
                telemetry.get_histogram(
                    "serve_request_seconds",
                    "handle-call latency (send to completion)",
                    tag_keys=("app", "deployment")).observe(
                    dur / 1e9, tags={"app": self.app_name,
                                     "deployment": self.deployment_name})
                if telemetry.enabled():
                    telemetry.complete(
                        "serve.request", "serve", t0_wall, dur,
                        app=self.app_name, deployment=self.deployment_name,
                        method=self._method, stream=self._stream,
                        trace_id=trace_id)

        threading.Thread(target=_done_watcher, daemon=True).start()
        return resp
