"""ServeController: the singleton reconciliation actor.

Capability parity: reference python/ray/serve/_private/controller.py:88 +
application_state.py + deployment_state.py — target-state reconciliation loop,
replica health checks, rolling updates on version change, request-rate autoscaling
(autoscaling_state.py). Handles/proxies poll get_routing_table() (long-poll analog).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.actor import method as _actor_method

CONTROLLER_NAME = "SERVE_CONTROLLER"

STARTING, RUNNING, STOPPING = "STARTING", "RUNNING", "STOPPING"
# graceful exit: out of the long-poll view immediately (no new requests),
# in-flight requests get up to drain_timeout_s to finish, then the kill
DRAINING = "DRAINING"


import itertools as _it

logger = logging.getLogger("ray_tpu.serve.controller")

_replica_uid = _it.count(1)


def _is_head_unavailable(err: BaseException) -> bool:
    """Head outage vs replica death: a health probe that failed because the
    CONTROL PLANE went away says nothing about the replica process, which
    keeps running on its agent. The reconciler must not turn a head blip into
    a replica-replacement storm (mirrors handle.is_head_unavailable; kept
    local so the controller has no import edge into the handle module)."""
    from ray_tpu.core.exceptions import HeadUnavailableError, TaskError

    if isinstance(err, TaskError):
        return isinstance(err.cause, HeadUnavailableError)
    return isinstance(err, HeadUnavailableError)


class _ReplicaState:
    def __init__(self, actor, version):
        self.actor = actor
        self.version = version
        self.uid = next(_replica_uid)  # stable identity (id() can be reused by GC)
        self.state = STARTING
        self.started_at = time.time()  # stuck-STARTING detection (autoscaler)
        self.health_ref = None
        self.last_health_ok = time.time()
        self.node_id: Optional[str] = None  # packing assignment (soft affinity)
        self.drain_deadline: Optional[float] = None
        self.drain_ref = None  # outstanding drain()/num_inflight() poll


class _DeploymentState:
    """Reference deployment_state.py:1379 — one deployment's replica set."""

    def __init__(self, name: str, app_name: str, info: Dict[str, Any]):
        self.name = name
        self.app_name = app_name
        self.info = info  # serialized_init, config, route_prefix, is_ingress
        self.replicas: List[_ReplicaState] = []
        self.target_num: int = info["config"].num_replicas or 1
        ac = info["config"].autoscaling_config
        if ac:
            self.target_num = max(ac.min_replicas, 1)
        self.autoscale_metric: float = 0.0
        self._last_scale_change = 0.0
        self.deleting = False  # drain-down in progress; reap when empty

    def running(self) -> List[_ReplicaState]:
        return [r for r in self.replicas if r.state == RUNNING]

    def in_state(self, state: str) -> List[_ReplicaState]:
        return [r for r in self.replicas if r.state == state]

    def drain_timeout_s(self) -> float:
        # pre-upgrade KV checkpoints may lack the field (unpickle skips
        # defaults); 0 is a real value ("no grace, kill immediately")
        v = getattr(self.info["config"], "drain_timeout_s", None)
        return 30.0 if v is None else v


class ServeController:
    def __init__(self):
        self.deployments: Dict[str, _DeploymentState] = {}  # key: app/deployment
        self.apps: Dict[str, Dict[str, Any]] = {}  # app -> {route_prefix, ingress, deployments}
        self._lock = threading.RLock()
        self._shutdown = False
        # reconcile-loop warning throttle (the loop runs several times/s)
        from ray_tpu.util.logutil import LogThrottle

        self._loop_warn = LogThrottle(30.0)
        # long-poll host state (reference _private/long_poll.py LongPollHost):
        # versioned keys; listeners block until a key they watch moves
        self._lp_versions: Dict[str, int] = {}
        self._lp_cond = threading.Condition()
        self._lp_last_running: Dict[str, tuple] = {}
        # recover target state checkpointed in the GCS KV (reference: serve app
        # state persisted in GCS KV; with RAY_TPU_GCS_PERSISTENCE_PATH it even
        # survives full cluster restarts)
        try:
            self._restore_from_kv()
        except Exception as e:
            logger.warning("serve state restore from KV failed (%r): "
                           "starting with no applications", e)
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # -- target-state checkpointing (reference: GCS KV-backed serve state) -------
    _KV_NS = "serve"

    def _checkpoint_app(self, app_name: str, route_prefix: str,
                        deployments: List[Dict[str, Any]]) -> None:
        import cloudpickle

        from ray_tpu.experimental import internal_kv

        blob = cloudpickle.dumps({"route_prefix": route_prefix, "deployments": deployments})
        internal_kv._internal_kv_put(b"app::" + app_name.encode(), blob,
                                     namespace=self._KV_NS)

    def _drop_checkpoint(self, app_name: str) -> None:
        from ray_tpu.experimental import internal_kv

        internal_kv._internal_kv_del(b"app::" + app_name.encode(), namespace=self._KV_NS)

    def _restore_from_kv(self) -> None:
        import cloudpickle

        from ray_tpu.experimental import internal_kv

        for key in internal_kv._internal_kv_list(b"app::", namespace=self._KV_NS):
            blob = internal_kv._internal_kv_get(key, namespace=self._KV_NS)
            if not blob:
                continue
            try:
                spec = cloudpickle.loads(blob)
                self.deploy_application(key[len(b"app::"):].decode(),
                                        spec["route_prefix"], spec["deployments"],
                                        _checkpoint=False)
            except Exception as e:
                # a stale/unloadable app must not block the rest
                logger.warning("could not restore serve app %r from its "
                               "checkpoint (%r); skipping it",
                               key[len(b"app::"):].decode(), e)
                continue

    # -- deploy API ------------------------------------------------------------
    def deploy_application(self, app_name: str, route_prefix: str,
                           deployments: List[Dict[str, Any]], _checkpoint: bool = True) -> None:
        """deployments: [{name, serialized_init, config, is_ingress}]"""
        with self._lock:
            # checkpoint under the lock: a concurrent delete must not interleave
            # between the KV write and the in-memory update (resurrection risk)
            if _checkpoint:
                try:
                    self._checkpoint_app(app_name, route_prefix, deployments)
                # graftlint: allow[swallowed-exception] checkpointing is best-effort; serving must not depend on it
                except Exception:
                    pass  # checkpointing is best-effort; serving must not depend on it
            self.apps[app_name] = {
                "route_prefix": route_prefix,
                "ingress": next(d["name"] for d in deployments if d["is_ingress"]),
                "deployments": [d["name"] for d in deployments],
            }
            for d in deployments:
                key = f"{app_name}/{d['name']}"
                existing = self.deployments.get(key)
                if existing is not None and existing.deleting:
                    # re-deploy racing a drain-down: resurrect as a rolling
                    # update (old draining replicas finish; fresh ones start)
                    existing.deleting = False
                if existing is not None and existing.info["config"].version != d["config"].version:
                    # version change -> rolling update: old replicas DRAIN
                    # (finish in-flight work) while replacements start
                    existing.info = d
                    for r in existing.replicas:
                        if r.version != d["config"].version and r.state in (STARTING, RUNNING):
                            self._drain_replica(r, existing)
                    existing.target_num = d["config"].num_replicas or existing.target_num
                elif existing is None:
                    self.deployments[key] = _DeploymentState(d["name"], app_name, d)
                else:
                    existing.info = d
                    if d["config"].num_replicas:
                        existing.target_num = d["config"].num_replicas
        # draining replicas must leave the long-poll view NOW, not a reconcile
        # tick later — handles stop picking them before the kill window opens
        self._publish_changes()

    def delete_application(self, app_name: str) -> None:
        """Drain-down, not a massacre: replicas finish in-flight requests (up
        to drain_timeout_s) before the reconcile loop reaps them."""
        with self._lock:
            try:
                self._drop_checkpoint(app_name)
            # graftlint: allow[swallowed-exception] checkpoint drop is best-effort; stale blobs are skipped on restore
            except Exception:
                pass
            app = self.apps.pop(app_name, None)
            if not app:
                return
            for dname in app["deployments"]:
                ds = self.deployments.get(f"{app_name}/{dname}")
                if ds:
                    ds.deleting = True
                    ds.target_num = 0
                    for r in ds.replicas:
                        if r.state in (STARTING, RUNNING):
                            self._drain_replica(r, ds)
        self._publish_changes()

    def shutdown(self) -> None:
        """Graceful stop: every replica drains (bounded by its deployment's
        drain_timeout_s) before the kill. Idle replicas cost one RPC round."""
        import ray_tpu

        for app in list(self.apps):
            self.delete_application(app)
        with self._lock:
            self._shutdown = True  # reconcile loop stops; we finish the drain
        # let any in-progress reconcile pass finish before we touch replica
        # state (drain_ref and the kill below run without the lock held)
        try:
            self._reconcile_thread.join(timeout=10)
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        with self._lock:
            pending = [(r, ds) for ds in self.deployments.values()
                       for r in ds.replicas]
        now = time.time()
        # honor drains already in progress (delete_application stamped their
        # deadline): shutdown must not grant a wedged replica a fresh window
        deadline_of = {id(r): (r.drain_deadline if r.drain_deadline is not None
                               else now + ds.drain_timeout_s())
                       for r, ds in pending}
        while pending:
            now = time.time()
            still = []
            polls = []
            for r, ds in pending:
                if now > deadline_of[id(r)]:
                    self._stop_replica(r)  # drain deadline burned: kill anyway
                    continue
                try:
                    polls.append((r, ds, r.drain_ref or r.actor.num_inflight.remote()))
                # graftlint: allow[swallowed-exception] an unusable handle means the replica is gone: it is reaped right here
                except Exception:
                    self._stop_replica(r)  # handle already unusable
            for r, ds, ref in polls:
                r.drain_ref = None
                try:
                    n = ray_tpu.get(ref, timeout=2.0)
                # graftlint: allow[swallowed-exception] degrades to the coded fallback (n = 0) by design
                except Exception:
                    n = 0  # replica already gone: nothing left to drain
                if n == 0:
                    self._stop_replica(r)
                else:
                    still.append((r, ds))
            pending = still
            if pending:
                time.sleep(0.05)
        with self._lock:
            for ds in self.deployments.values():
                ds.replicas.clear()
            self.deployments.clear()
        with self._lp_cond:  # wake parked listeners so they return promptly
            self._lp_cond.notify_all()

    def _drain_replica(self, r: _ReplicaState, ds: _DeploymentState) -> None:
        """RUNNING/STARTING -> DRAINING (caller holds the lock). The drain()
        RPC flips the replica's gate so racing sends bounce to live replicas;
        its reply doubles as the first in-flight poll."""
        r.state = DRAINING
        r.drain_deadline = time.time() + ds.drain_timeout_s()
        r.health_ref = None
        try:
            r.drain_ref = r.actor.drain.remote()
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (r.drain_ref = None) by design
        except Exception:
            r.drain_ref = None  # dead already; reconcile reaps it

    # -- read APIs (handles/proxies poll these; reference LongPollHost) ---------
    def get_routing_table(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for app_name, app in self.apps.items():
                key = f"{app_name}/{app['ingress']}"
                ds = self.deployments.get(key)
                out[app["route_prefix"]] = {
                    "app": app_name,
                    "deployment": app["ingress"],
                    "replicas": [r.actor for r in ds.running()] if ds else [],
                }
            return out

    def get_replicas(self, app_name: str, deployment_name: str) -> List[Any]:
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            return [r.actor for r in ds.running()] if ds else []

    def get_deployment_info(self, app_name: str, deployment_name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is None:
                return None
            return {
                "target_num_replicas": ds.target_num,
                "num_running": len(ds.running()),
                "states": [r.state for r in ds.replicas],
            }

    def get_deployment_limits(self, app_name: str,
                              deployment_name: str) -> Optional[Dict[str, Any]]:
        """Admission/retry knobs the handle enforces client-side (cached there;
        getattr guards cover pre-upgrade KV checkpoints missing new fields)."""
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is None:
                return None
            cfg = ds.info["config"]
            # target-aware admission: while a scale change is YOUNG the handle
            # sizes capacity on the target (arriving replicas will absorb the
            # queue); once the startup window burns without the fleet reaching
            # it, anticipation expires and shedding resumes on real capacity
            from ray_tpu.config import CONFIG

            young = (time.time() - ds._last_scale_change
                     <= CONFIG.serve_autoscale_startup_timeout_s)
            running = len(ds.running())
            return {
                "max_ongoing_requests": getattr(cfg, "max_ongoing_requests", 8),
                "max_queued_requests": getattr(cfg, "max_queued_requests", -1),
                "retryable": getattr(cfg, "retryable", True),
                "target_num_replicas": ds.target_num,
                "anticipated_replicas": ds.target_num if young else running,
            }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                app: {
                    "route_prefix": info["route_prefix"],
                    "deployments": {
                        d: self.get_deployment_info(app, d) for d in info["deployments"]
                    },
                }
                for app, info in self.apps.items()
            }

    def ping(self) -> bool:
        return True

    def report_replica_failure(self, app_name: str, deployment_name: str,
                               actor_id) -> bool:
        """Handle-side death push: a client observed an authoritative
        ActorDiedError/WorkerCrashedError on this replica. Mark it STOPPING
        and republish NOW instead of letting it sit in the routing view for
        up to health_check_period_s — the window where a scale-down could
        otherwise drain the healthy replicas and keep the dead one."""
        marked = False
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is None:
                return False
            for r in ds.replicas:
                if r.actor._actor_id == actor_id and r.state in (STARTING,
                                                                 RUNNING,
                                                                 DRAINING):
                    r.state = STOPPING
                    r.health_ref = None
                    marked = True
        if marked:
            self._publish_changes()  # dead replica leaves the view immediately
        return marked

    # -- autoscaling input (handles push router stats; reference autoscaling_state) --
    def record_handle_metrics(self, app_name: str, deployment_name: str, ongoing: float) -> None:
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is not None:
                # EWMA smooth so momentary spikes don't flap the replica count
                ds.autoscale_metric = 0.6 * ds.autoscale_metric + 0.4 * ongoing

    # -- SLO-loop autoscaling surface (head-side serve/autoscaler.py) -----------
    @staticmethod
    def _ac_mode(ds: _DeploymentState) -> Optional[str]:
        ac = ds.info["config"].autoscaling_config
        if ac is None:
            return None
        # pre-upgrade KV checkpoints may lack the field (unpickle skips defaults)
        return getattr(ac, "mode", "ongoing")

    def get_autoscale_state(self) -> Dict[str, Dict[str, Any]]:
        """Everything the head-side loop needs to re-derive its decisions,
        keyed "app/deployment" — only deployments opted into mode="slo".
        Served fresh on every tick so a restarted head resumes from the
        KV-restored app configs, not anyone's in-memory state."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for key, ds in self.deployments.items():
                if ds.deleting or self._ac_mode(ds) != "slo":
                    continue
                ac = ds.info["config"].autoscaling_config
                opts = dict(ds.info["config"].ray_actor_options or {})
                shape = {"CPU": float(opts.get("num_cpus", 1))}
                if opts.get("num_tpus"):
                    shape["TPU"] = float(opts["num_tpus"])
                route = ""
                app = self.apps.get(ds.app_name)
                if app:
                    route = app.get("route_prefix", "")
                out[key] = {
                    "app": ds.app_name,
                    "deployment": ds.name,
                    "target": ds.target_num,
                    "running": len(ds.running()),
                    "starting": len(ds.in_state(STARTING)),
                    "draining": len(ds.in_state(DRAINING)),
                    "min_replicas": ac.min_replicas,
                    "max_replicas": ac.max_replicas,
                    "target_queue_depth": getattr(ac, "target_queue_depth",
                                                  None),
                    "slo_names": getattr(ac, "slo_names", None),
                    "resource_shape": shape,
                    "route_prefix": route,
                }
        return out

    def set_autoscale_target(self, app_name: str, deployment_name: str,
                             target: int, reason: str = "") -> Optional[int]:
        """Apply one autoscaler decision. Clamped to the deployment's
        [max(1, min_replicas), max_replicas] — the control loop can never
        order the last healthy replica killed — and executed by the reconcile
        loop through the normal DRAINING choreography. Returns the clamped
        target actually set, or None when the deployment is gone or
        mid-delete (the caller must not record a scale that never happened)."""
        from ray_tpu.util import fault_injection

        fault_injection.fail_point(
            "serve.controller.scale", app=app_name,
            deployment=deployment_name, target=target, reason=reason)
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is None or ds.deleting:
                return None
            ac = ds.info["config"].autoscaling_config
            lo = max(1, ac.min_replicas) if ac else 1
            hi = ac.max_replicas if ac else max(lo, int(target))
            clamped = max(lo, min(hi, int(target)))
            if clamped != ds.target_num:
                logger.info("autoscale target %s/%s: %d -> %d (%s)",
                            app_name, deployment_name, ds.target_num,
                            clamped, reason or "unspecified")
                ds.target_num = clamped
                ds._last_scale_change = time.time()
            return clamped

    def restart_stuck_replicas(self, app_name: str, deployment_name: str,
                               older_than_s: float = 30.0) -> int:
        """Kill STARTING replicas wedged past `older_than_s` so the reconcile
        loop reschedules them (the soft node-affinity re-picks placement —
        possibly a different, newly launched node). The autoscaler calls this
        when a scale-up never becomes healthy."""
        now = time.time()
        n = 0
        with self._lock:
            ds = self.deployments.get(f"{app_name}/{deployment_name}")
            if ds is None:
                return 0
            for r in ds.replicas:
                if r.state == STARTING and now - r.started_at >= older_than_s:
                    r.state = STOPPING  # reconcile reaps + restarts elsewhere
                    r.health_ref = None
                    n += 1
        if n:
            logger.warning(
                "%s/%s: restarting %d replica(s) stuck in STARTING longer "
                "than %.0fs", app_name, deployment_name, n, older_than_s)
        return n

    # -- chaos hooks (ChaosController.arm_serve_controller) ---------------------
    def _arm_fault(self, site: str, mode: str = "error", prob: float = 1.0,
                   count: Optional[int] = None, delay_s: float = 0.0,
                   seed: Optional[int] = None) -> bool:
        """Arm a fail point in the CONTROLLER process (e.g.
        serve.controller.scale), so chaos runs can kill the scale path
        mid-decision."""
        from ray_tpu.util import fault_injection

        fault_injection.arm(site, mode, prob, count, delay_s, seed)
        return True

    def _disarm_fault(self, site: Optional[str] = None) -> bool:
        from ray_tpu.util import fault_injection

        fault_injection.disarm(site)
        return True

    # -- reconciliation --------------------------------------------------------
    def _choose_replica_node(self, ds: _DeploymentState,
                             num_cpus: float) -> Optional[str]:
        """Replica->node packing (reference _private/deployment_scheduler.py):
        PACK fills the node already hosting the most of this deployment's
        replicas (compact; whole nodes free up for downscaling), SPREAD picks
        the one hosting the fewest. Returns a node id hex, or None to let the
        default scheduler place."""
        try:
            from ray_tpu.util.state import list_nodes

            nodes = [n for n in list_nodes() if n["alive"]]
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return None) by design
        except Exception:
            return None
        if len(nodes) <= 1:
            return None
        counts = {n["node_id"]: 0 for n in nodes}
        for r in ds.replicas:
            if r.node_id in counts:
                counts[r.node_id] += 1
        fits = [n for n in nodes
                if n["resources_available"].get("CPU", 0.0) >= num_cpus]
        if not fits:
            return None
        # pre-upgrade KV checkpoints may lack the field (unpickle skips defaults)
        spread = getattr(ds.info["config"], "placement_strategy", "PACK") == "SPREAD"
        best = min(fits, key=lambda n: counts[n["node_id"]]) if spread else \
            max(fits, key=lambda n: counts[n["node_id"]])
        return best["node_id"]

    def _start_replica(self, ds: _DeploymentState) -> None:
        import ray_tpu

        opts = dict(ds.info["config"].ray_actor_options or {})
        actor_opts = {"num_cpus": opts.get("num_cpus", 1)}
        if opts.get("num_tpus"):
            actor_opts["num_tpus"] = opts["num_tpus"]
        # replicas serve concurrent requests up to max_ongoing_requests
        # (threaded actor) — the replica-side half of admission control: the
        # runtime caps executing user requests at moq, excess queues in the
        # mailbox. Control RPCs (health/drain/fault-arming) run on their own
        # unbounded group so a saturated replica still answers the controller.
        moq = ds.info["config"].max_ongoing_requests
        actor_opts["max_concurrency"] = max(1, moq or 1)
        actor_opts["concurrency_groups"] = {"control": 0}
        node_id = self._choose_replica_node(ds, actor_opts["num_cpus"])
        if node_id is not None:
            from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

            # soft: if the chosen node fills up meanwhile, fall through rather
            # than wedging the deployment
            actor_opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                node_id=node_id, soft=True)
        from .replica import Replica

        cls = ray_tpu.remote(**actor_opts)(Replica)
        actor = cls.remote(ds.name, ds.info["serialized_init"],
                           ds.info["config"].user_config,
                           app_name=ds.app_name,
                           max_ongoing_requests=max(0, moq or 0))
        r = _ReplicaState(actor, ds.info["config"].version)
        r.node_id = node_id
        r.health_ref = actor.check_health.remote()
        ds.replicas.append(r)

    def _stop_replica(self, r: _ReplicaState) -> None:
        import ray_tpu

        try:
            r.actor.prepare_shutdown.remote()
            ray_tpu.kill(r.actor, no_restart=True)
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass

    def _autoscale(self, ds: _DeploymentState, now: float) -> None:
        ac = ds.info["config"].autoscaling_config
        if ac is None:
            return
        if self._ac_mode(ds) == "slo":
            # the head-side SLO loop owns this deployment's target
            # (set_autoscale_target); the request-rate rule stepping on it
            # would thrash the replica count between two masters
            return
        desired = ds.autoscale_metric / max(ac.target_ongoing_requests, 1e-6)
        import math

        desired = int(math.ceil(desired))
        desired = max(ac.min_replicas, min(ac.max_replicas, desired))
        if desired > ds.target_num and now - ds._last_scale_change >= ac.upscale_delay_s:
            ds.target_num = desired
            ds._last_scale_change = now
        elif desired < ds.target_num and now - ds._last_scale_change >= ac.downscale_delay_s:
            ds.target_num = desired
            ds._last_scale_change = now

    def _reconcile_once(self) -> None:
        import ray_tpu

        now = time.time()
        with self._lock:
            states = list(self.deployments.values())
        for ds in states:
            with self._lock:
                self._autoscale(ds, now)
                # promote STARTING replicas whose health check came back
                for r in ds.replicas:
                    if r.state == STARTING and r.health_ref is not None:
                        done, _ = ray_tpu.wait([r.health_ref], num_returns=1, timeout=0)
                        if done:
                            try:
                                ray_tpu.get(r.health_ref)
                                r.state = RUNNING
                                r.last_health_ok = now
                                r.health_ref = None
                            except Exception as e:
                                if _is_head_unavailable(e):
                                    # control-plane outage, not replica death:
                                    # the reply died with the old head. The
                                    # replica process is untouched — ask again
                                    # instead of replacing a healthy worker.
                                    r.last_health_ok = now
                                    r.health_ref = r.actor.check_health.remote()
                                else:
                                    logger.warning(
                                        "%s replica #%s failed its startup health "
                                        "check (%r); replacing it", ds.name, r.uid, e)
                                    r.state = STOPPING
                                    r.health_ref = None
                # periodic health checks on RUNNING replicas
                period = ds.info["config"].health_check_period_s
                for r in ds.replicas:
                    if r.state == RUNNING and r.health_ref is None and now - r.last_health_ok > period:
                        r.health_ref = r.actor.check_health.remote()
                    elif r.state == RUNNING and r.health_ref is not None:
                        done, _ = ray_tpu.wait([r.health_ref], num_returns=1, timeout=0)
                        if done:
                            try:
                                ray_tpu.get(r.health_ref)
                                r.last_health_ok = now
                            except Exception as e:
                                if _is_head_unavailable(e):
                                    # inconclusive: the head blinked, the
                                    # replica didn't. Grant outage grace and
                                    # re-check a full period from now.
                                    r.last_health_ok = now
                                else:
                                    logger.warning(
                                        "%s replica #%s failed its health check "
                                        "(%r); replacing it", ds.name, r.uid, e)
                                    r.state = STOPPING
                            r.health_ref = None
                        elif now - r.last_health_ok > period + ds.info["config"].health_check_timeout_s:
                            r.state = STOPPING
                            r.health_ref = None
                # DRAINING: poll in-flight; drained (or past deadline) -> STOPPING
                for r in [x for x in ds.replicas if x.state == DRAINING]:
                    if r.drain_ref is None:
                        try:
                            r.drain_ref = r.actor.num_inflight.remote()
                        # graftlint: allow[swallowed-exception] degrades to the coded fallback (r.state = STOPPING) by design
                        except Exception:
                            r.state = STOPPING  # handle unusable: reap now
                            continue
                    done, _ = ray_tpu.wait([r.drain_ref], num_returns=1, timeout=0)
                    if done:
                        try:
                            n = ray_tpu.get(r.drain_ref)
                        # graftlint: allow[swallowed-exception] degrades to the coded fallback (n = 0) by design
                        except Exception:
                            n = 0  # replica died mid-drain: nothing left to wait on
                        r.drain_ref = None
                        if n == 0:
                            r.state = STOPPING
                    if r.state == DRAINING and r.drain_deadline is not None \
                            and now > r.drain_deadline:
                        r.state = STOPPING  # grace burned: kill anyway
                # remove STOPPING
                for r in [x for x in ds.replicas if x.state == STOPPING]:
                    self._stop_replica(r)
                    ds.replicas.remove(r)
                # scale to target: count live (non-stopping, non-draining)
                # replicas of the current version
                live = [r for r in ds.replicas if r.state in (STARTING, RUNNING)]
                if not ds.deleting:
                    for _ in range(ds.target_num - len(live)):
                        self._start_replica(ds)
                extra = len(live) - ds.target_num
                for r in reversed(live):
                    if extra <= 0:
                        break
                    if r.state == RUNNING or r.state == STARTING:
                        self._drain_replica(r, ds)  # graceful scale-down
                        extra -= 1
        # reap deployments whose drain-down finished (app already deleted)
        with self._lock:
            for key in [k for k, ds in self.deployments.items()
                        if ds.deleting and not ds.replicas]:
                del self.deployments[key]

    def _reconcile_loop(self) -> None:
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception as e:
                if self._loop_warn.ready("reconcile"):
                    logger.warning("serve reconcile pass failed (suppressed "
                                   "for 30s): %r", e)
            try:
                # never skipped: a throwing reconcile pass (e.g. one poisoned
                # deployment) must not silence membership publishing for the rest
                self._publish_changes()
            except Exception as e:
                if self._loop_warn.ready("publish"):
                    logger.warning("serve long-poll publish failed "
                                   "(suppressed for 30s): %r", e)
            from ray_tpu.config import CONFIG as _CFG

            time.sleep(_CFG.serve_reconcile_interval_s)

    # -- long-poll host (reference LongPollHost) --------------------------------
    def _publish_changes(self) -> None:
        """Bump versions for deployments whose running replica set changed."""
        t0 = time.perf_counter()
        with self._lock:
            snapshots = {
                key: tuple(r.uid for r in ds.running())
                for key, ds in self.deployments.items()
            }
        changed = [k for k, snap in snapshots.items() if self._lp_last_running.get(k) != snap]
        gone = [k for k in self._lp_last_running if k not in snapshots]
        if not changed and not gone:
            return
        with self._lp_cond:
            for k in changed:
                self._lp_last_running[k] = snapshots[k]
                self._lp_versions[f"replicas::{k}"] = self._lp_versions.get(f"replicas::{k}", 0) + 1
            for k in gone:
                self._lp_last_running.pop(k, None)
                self._lp_versions[f"replicas::{k}"] = self._lp_versions.get(f"replicas::{k}", 0) + 1
            self._lp_versions["routes"] = self._lp_versions.get("routes", 0) + 1
            self._lp_cond.notify_all()
        # control-plane self-telemetry: long-poll fan-out cost (snapshot diff
        # + version bumps + waking every parked listener)
        from ray_tpu.util import telemetry as _tel

        _tel.get_histogram(
            "control_decision_seconds",
            "wall time of one control-loop decision pass, by loop",
            tag_keys=("loop",),
        ).observe(time.perf_counter() - t0, tags={"loop": "serve_publish"})

    @_actor_method(concurrency_group="listen")
    def listen_for_change(self, keys_to_versions: Dict[str, int],
                          timeout_s: float = 10.0) -> Dict[str, Any]:
        """Block until any watched key's version differs from the caller's view;
        returns {key: (new_version, snapshot)} ({} on timeout). Runs on the
        unbounded "listen" concurrency group (see serve/api.py) so parked
        listeners never starve deploy/reconcile APIs on the default pool."""
        deadline = time.monotonic() + timeout_s
        with self._lp_cond:
            while not self._shutdown:
                changed = {
                    k: self._lp_versions.get(k, 0)
                    for k, v in keys_to_versions.items()
                    if self._lp_versions.get(k, 0) != v
                }
                if changed:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._lp_cond.wait(remaining)
            else:
                return {}
        return {k: (ver, self._lp_snapshot(k)) for k, ver in changed.items()}

    def _lp_snapshot(self, key: str) -> Any:
        kind, _, ident = key.partition("::")
        if kind == "replicas":
            app, _, dep = ident.partition("/")
            with self._lock:
                if f"{app}/{dep}" not in self.deployments:
                    return None  # deleted: listeners stop watching this key
            return self.get_replicas(app, dep)
        if key == "routes":
            return self.get_routing_table()
        return None
