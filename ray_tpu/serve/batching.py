"""@serve.batch: dynamic request batching.

Capability parity: reference python/ray/serve/batching.py — queue calls until
max_batch_size or batch_wait_timeout_s, invoke the wrapped fn once with the list of
inputs, scatter results. Thread-based (replicas execute requests on worker threads).
"""
from __future__ import annotations

import functools
import queue as _queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]], max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.q: _queue.Queue = _queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-batch-loop")
        self._thread.start()

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        self.q.put((instance, item, fut))
        return fut

    def _loop(self) -> None:
        while True:
            instance, item, fut = self.q.get()
            batch = [(instance, item, fut)]
            # drain up to max_batch_size within the wait timeout
            import time

            deadline = time.monotonic() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except _queue.Empty:
                    break
            items = [b[1] for b in batch]
            inst = batch[0][0]
            try:
                results = self.fn(inst, items) if inst is not None else self.fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch fn returned {len(results)} results for {len(items)} inputs"
                    )
                for (_, _, f), r in zip(batch, results):
                    f.set_result(r)
            except BaseException as e:  # noqa: BLE001
                for _, _, f in batch:
                    if not f.done():
                        f.set_exception(e)


_creation_lock = threading.Lock()


def _get_batcher(wrapper, fn, max_batch_size: int, timeout_s: float) -> _Batcher:
    b = getattr(wrapper, "_batcher", None)
    if b is None:
        with _creation_lock:
            b = getattr(wrapper, "_batcher", None)
            if b is None:
                b = _Batcher(fn, max_batch_size, timeout_s)
                wrapper._batcher = b
    return b


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorator: fn(self, requests: List) -> List (reference @serve.batch)."""

    def wrap(fn):
        # The batcher (thread + queue + locks) is created lazily in the process that
        # first calls the wrapper — unpicklable state must not live in the closure,
        # since deployment classes are cloudpickled to replicas.
        is_method = "." in getattr(fn, "__qualname__", "")

        if is_method:
            @functools.wraps(fn)
            def method_wrapper(self, item):
                return _get_batcher(method_wrapper, fn, max_batch_size, batch_wait_timeout_s).submit(self, item).result()

            return method_wrapper

        @functools.wraps(fn)
        def fn_wrapper(item):
            return _get_batcher(fn_wrapper, fn, max_batch_size, batch_wait_timeout_s).submit(None, item).result()

        return fn_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
