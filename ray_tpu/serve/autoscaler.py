"""Serve autoscaling control loop: SLO-burn-driven replica targets.

PR 8 built the control inputs (windowed quantiles, SRE multi-window burn
rates, ``subscribe_slo()`` transitions) and PR 11 built the actuators
(graceful DRAINING, suspect routing, admission control) — this module closes
the loop. The same decoupled control-plane discipline Podracer (2104.06272)
applies to RL actors/learners, applied to serve replicas:

    scrape -> metrics_history -> SLO engine -> AutoscalePolicy -> controller
      ^                                                               |
      '-------------------- replicas start/DRAIN <-------------------'

Pieces:

- :class:`AutoscalePolicy` — the pure decision core, one instance per loop.
  Inputs are :class:`DeploymentSnapshot` rows (current target, live/starting/
  draining counts, cluster-wide queue depth, whether a matching SLO is
  burning); output is a desired target plus a reason. Hysteresis is built in:
  scale-up needs the burn/queue pressure sustained for
  ``RAY_TPU_SERVE_AUTOSCALE_BURN_TICKS`` consecutive ticks, scale-down needs
  ``RAY_TPU_SERVE_AUTOSCALE_CLEAN_TICKS`` clean ticks AND the down-cooldown
  elapsed AND no replica still draining (drain capacity exists) — a flapping
  SLO holds the fleet steady instead of thrashing the paged-KV pool. The
  floor is ``max(1, min_replicas)``: the loop never kills the last healthy
  replica.
- :class:`ServeAutoscalerLoop` — the head-side daemon thread. Paced by the
  metrics-history scraper (frame subscription) and woken early by
  ``subscribe_slo()`` transitions; every tick it re-derives the world from
  the controller (``get_autoscale_state``) and the head's metrics history —
  NO in-memory target state survives a head restart, so a reattached head
  resumes from the controller's KV-restored app configs. Decisions are
  applied through ``controller.set_autoscale_target`` (the existing DRAINING
  choreography does the rest) and journaled three ways: a bounded in-memory
  journal (``ray-tpu status``), ``serve.autoscale`` telemetry spans, and the
  ``serve_autoscale_decisions_total{reason}`` counter.
- Stuck scale-ups (a target the fleet never reaches — no host has room, or a
  replica wedges in STARTING) time out after
  ``RAY_TPU_SERVE_AUTOSCALE_STARTUP_TIMEOUT_S``: the deficit is posted as a
  demand hint to the node :class:`~ray_tpu.autoscaler.Autoscaler`'s
  bin-packing (new capacity), wedged STARTING replicas are restarted so they
  can land elsewhere, and the handle's anticipated-capacity admission window
  expires so callers are shed again (see handle._maybe_shed).

Fault injection: ``serve.autoscaler.decide`` fires at the top of every tick
(error mode = decision crash, absorbed + journaled; kill mode = the head
dies, the reattach path restarts the loop), ``serve.controller.scale`` fires
inside the controller's apply RPC.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.serve.autoscaler")

AUTOSCALER_THREAD_NAME = "serve-autoscaler"


# --------------------------------------------------------------------- policy

@dataclasses.dataclass
class DeploymentSnapshot:
    """One deployment's world-state for one policy tick. Built by the loop
    from the controller's autoscale state + the head's metrics history; in
    tests, built synthetically — the policy never reads globals."""

    key: str  # "app/deployment"
    target: int
    running: int
    starting: int
    draining: int
    min_replicas: int
    max_replicas: int
    queue_depth: float  # cluster-wide in-flight for this deployment
    queue_target: float  # desired in-flight per replica
    burning: bool  # any matching SLO currently burning
    now: float  # monotonic seconds (injectable for tests)


@dataclasses.dataclass
class Decision:
    key: str
    target: int  # current controller target
    desired: int
    reason: str

    @property
    def changed(self) -> bool:
        return self.desired != self.target


class _DeploymentPolicyState:
    __slots__ = ("burn_ticks", "clean_ticks", "pressure_ticks",
                 "last_scale_up", "last_scale_down", "deficit_since")

    def __init__(self):
        self.burn_ticks = 0
        self.clean_ticks = 0
        self.pressure_ticks = 0
        self.last_scale_up: Optional[float] = None
        self.last_scale_down: Optional[float] = None
        self.deficit_since: Optional[float] = None


class AutoscalePolicy:
    """Per-deployment hysteresis + cooldown state around a pure decision
    rule. ``decide()`` mutates only tick counters; cooldown stamps move in
    ``commit()`` so a decision the controller RPC LOST does not burn the
    cooldown (the next tick retries the same decision)."""

    def __init__(self, *, burn_ticks: int = 2, clean_ticks: int = 3,
                 up_cooldown_s: float = 3.0, down_cooldown_s: float = 30.0,
                 startup_timeout_s: float = 30.0):
        self.burn_ticks_needed = max(1, int(burn_ticks))
        self.clean_ticks_needed = max(1, int(clean_ticks))
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.startup_timeout_s = float(startup_timeout_s)
        self._state: Dict[str, _DeploymentPolicyState] = {}

    def _st(self, key: str) -> _DeploymentPolicyState:
        st = self._state.get(key)
        if st is None:
            st = self._state[key] = _DeploymentPolicyState()
        return st

    def prune(self, live_keys) -> None:
        """Forget deployments that left the autoscale view (app deleted)."""
        for key in [k for k in self._state if k not in live_keys]:
            del self._state[key]

    def decide(self, snap: DeploymentSnapshot) -> Decision:
        st = self._st(snap.key)
        floor = max(1, snap.min_replicas)
        ceil_ = max(floor, snap.max_replicas)

        # -- tick the hysteresis counters
        if snap.burning:
            st.burn_ticks += 1
            st.clean_ticks = 0
        else:
            st.burn_ticks = 0
            st.clean_ticks += 1
        per_replica = snap.queue_depth / max(1, snap.running)
        if snap.queue_target > 0 and per_replica > snap.queue_target:
            st.pressure_ticks += 1
        else:
            st.pressure_ticks = 0

        # -- bounds first: a target outside [floor, ceil] is corrected
        # immediately, cooldowns notwithstanding (a shrunk max must apply)
        if snap.target < floor:
            return Decision(snap.key, snap.target, floor, "min_floor")
        if snap.target > ceil_:
            return Decision(snap.key, snap.target, ceil_, "max_ceiling")

        # -- scale up: sustained SLO burn or sustained queue pressure
        burn_up = st.burn_ticks >= self.burn_ticks_needed
        queue_up = st.pressure_ticks >= self.burn_ticks_needed
        if (burn_up or queue_up) and snap.target < ceil_:
            if st.last_scale_up is not None \
                    and snap.now - st.last_scale_up < self.up_cooldown_s:
                return Decision(snap.key, snap.target, snap.target,
                                "up_cooldown")
            # queue math names the replica count that meets the per-replica
            # target; an SLO burn without queue signal steps by one
            desired = snap.target + 1
            if snap.queue_target > 0:
                import math

                desired = max(desired, math.ceil(
                    snap.queue_depth / snap.queue_target))
            desired = min(ceil_, desired)
            return Decision(snap.key, snap.target, desired,
                            "slo_burn" if burn_up else "queue_depth")

        # -- scale down: every window clean, cooldown elapsed, and the drain
        # plane idle (a pending drain means capacity is ALREADY leaving)
        if snap.target > floor \
                and st.clean_ticks >= self.clean_ticks_needed \
                and st.pressure_ticks == 0 \
                and snap.draining == 0 \
                and snap.running > 1:
            last = max(st.last_scale_down or 0.0, st.last_scale_up or 0.0)
            if snap.now - last < self.down_cooldown_s:
                return Decision(snap.key, snap.target, snap.target,
                                "down_cooldown")
            # one step at a time, and never below what the queue needs now
            desired = snap.target - 1
            if snap.queue_target > 0:
                import math

                desired = max(desired, math.ceil(
                    snap.queue_depth / snap.queue_target))
            desired = max(floor, min(snap.target, desired))
            if desired == snap.target:
                return Decision(snap.key, snap.target, snap.target, "hold")
            return Decision(snap.key, snap.target, desired, "clean_scale_down")

        return Decision(snap.key, snap.target, snap.target, "hold")

    def commit(self, decision: Decision, now: float) -> None:
        """The controller accepted this decision: stamp the cooldown."""
        st = self._st(decision.key)
        if decision.desired > decision.target:
            st.last_scale_up = now
            st.clean_ticks = 0
        elif decision.desired < decision.target:
            st.last_scale_down = now
        st.burn_ticks = 0
        st.pressure_ticks = 0

    def stuck_deficit(self, snap: DeploymentSnapshot) -> bool:
        """True when the fleet has been below target for longer than the
        startup timeout — the scale-up never became healthy (no room, or a
        wedged STARTING replica). Timer resets the moment the deficit closes."""
        st = self._st(snap.key)
        if snap.running >= snap.target:
            st.deficit_since = None
            return False
        if st.deficit_since is None:
            st.deficit_since = snap.now
            return False
        return snap.now - st.deficit_since >= self.startup_timeout_s


# ----------------------------------------------------------------------- loop

class ServeAutoscalerLoop:
    """Head-side control loop. One instance per head process, paced by the
    metrics scraper's frames and woken early by SLO transitions."""

    JOURNAL_SIZE = 128

    def __init__(self, cluster):
        from ray_tpu.config import CONFIG
        from ray_tpu.util.logutil import LogThrottle

        self.cluster = cluster
        self.policy = AutoscalePolicy(
            burn_ticks=CONFIG.serve_autoscale_burn_ticks,
            clean_ticks=CONFIG.serve_autoscale_clean_ticks,
            up_cooldown_s=CONFIG.serve_autoscale_up_cooldown_s,
            down_cooldown_s=CONFIG.serve_autoscale_down_cooldown_s,
            startup_timeout_s=CONFIG.serve_autoscale_startup_timeout_s)
        self._warn = LogThrottle(30.0)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._journal: deque = deque(maxlen=self.JOURNAL_SIZE)
        self._targets: Dict[str, Dict[str, Any]] = {}  # last-seen view
        self._hinted: set = set()  # deployments with a posted demand hint
        self.ticks = 0
        self._unsub_slo = None
        self._unsub_frames = None
        try:
            self._unsub_slo = cluster.slo_engine.subscribe(self._on_slo)
            self._unsub_frames = cluster.metrics_history.subscribe_frames(
                self._on_frame)
        except Exception as e:  # noqa: BLE001 — loop still paces on its timer
            logger.warning("serve autoscaler could not subscribe to the "
                           "scrape plane (%r); pacing on the fallback timer", e)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=AUTOSCALER_THREAD_NAME)
        self._thread.start()

    # -- wake sources ---------------------------------------------------------
    def _on_slo(self, transition: dict) -> None:
        # any burning<->ok flip re-evaluates immediately: scale-ups must not
        # wait out a sleeping tick
        self._wake.set()

    def _on_frame(self, _frame: dict) -> None:
        self._wake.set()

    # -- lifecycle ------------------------------------------------------------
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stop.is_set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for unsub in (self._unsub_slo, self._unsub_frames):
            if unsub is not None:
                try:
                    unsub()
                # graftlint: allow[swallowed-exception] unsubscribe from a cluster already torn down; nothing left to detach
                except Exception:
                    pass
        self._thread.join(timeout=2)
        # retract outstanding demand hints: a stopped loop must not keep
        # phantom serve demand in the node autoscaler's bin-packing forever
        with self._lock:
            hinted, self._hinted = set(self._hinted), set()
        for key in hinted:
            self._clear_demand_hint(key)

    def _interval_s(self) -> float:
        from ray_tpu.config import CONFIG

        explicit = float(CONFIG.serve_autoscale_interval_s)
        if explicit > 0:
            return explicit
        # frame-driven (default): the wait is only the fallback for a stalled
        # scraper, so pace it at the scrape interval (floored: scraping off)
        scrape = float(CONFIG.metrics_scrape_interval_s)
        return max(0.25, scrape) if scrape > 0 else 1.0

    def _run(self) -> None:
        from ray_tpu.core import global_state

        while not self._stop.is_set():
            self._wake.wait(timeout=self._interval_s())
            self._wake.clear()
            if self._stop.is_set():
                return
            if getattr(self.cluster, "_shutdown", False) \
                    or global_state.try_cluster() is not self.cluster:
                return  # head went away: a fresh head starts a fresh loop
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — decision crash: journaled
                # the decision path crashing must not kill the only control
                # loop; journal it so `ray-tpu status` explains the gap
                self._journal_event({"event": "decide_error",
                                     "error": repr(e)}, reason="decide_error")
                if self._warn.ready("tick"):
                    logger.warning("serve autoscaler tick failed (loop "
                                   "continues): %r", e)

    # -- journaling -----------------------------------------------------------
    def _journal_event(self, row: Dict[str, Any], reason: str) -> None:
        row = {"ts": time.time(), **row}
        with self._lock:
            self._journal.append(row)
        try:
            from ray_tpu.util import telemetry

            telemetry.get_counter(
                "serve_autoscale_decisions_total",
                "serve autoscaler decisions/outcomes by reason",
                tag_keys=("reason",)).inc(tags={"reason": reason})
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the control loop down
        except Exception:
            pass

    def status(self) -> Dict[str, Any]:
        """Introspection for `ray-tpu status` / state.serve_autoscaler_status:
        the last-seen per-deployment view plus the recent decision journal."""
        with self._lock:
            return {
                "alive": self.alive(),
                "ticks": self.ticks,
                "deployments": {k: dict(v) for k, v in self._targets.items()},
                "decisions": list(self._journal),
            }

    # -- one tick -------------------------------------------------------------
    def _controller(self):
        import ray_tpu
        from .controller import CONTROLLER_NAME

        try:
            return ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            return None  # serve not running: idle tick

    def _burning_names(self) -> Tuple[Dict[str, dict], List[Any]]:
        status = self.cluster.slo_engine.status()
        burning = {name: row for name, row in status.items()
                   if row.get("state") == "burning"}
        slos = {s.name: s for s in self.cluster.slo_engine.slos()}
        return burning, [slos.get(n) for n in burning]

    @staticmethod
    def _slo_matches(slo, row: dict, app: str, deployment: str,
                     route_prefix: str, slo_names) -> bool:
        """Does a burning SLO drive THIS deployment? Explicit slo_names pin
        it; otherwise the SLO's `where` tags scope it (no tags = fleet-wide)."""
        name = row.get("name") if isinstance(row, dict) else None
        if slo_names:
            return name in slo_names
        where = getattr(slo, "where", None) or {}
        if not where:
            return True
        if where.get("app") not in (None, app):
            return False
        if where.get("deployment") not in (None, deployment):
            return False
        route = where.get("route")
        if route is not None and route_prefix:
            # path-boundary match: "/chat2" must not count as under "/chat"
            rp = route_prefix.rstrip("/")
            if rp and route != route_prefix and route != rp \
                    and not route.startswith(rp + "/"):
                return False
        return True

    def _queue_depth(self, app: str, deployment: str) -> float:
        """Cluster-wide in-flight for the deployment: latest frame's
        proc-summed serve_queue_depth gauge (the same accounting
        cluster_status renders)."""
        from ray_tpu.config import CONFIG

        window = max(2.5 * float(CONFIG.metrics_scrape_interval_s or 1.0), 1.0)
        vals = self.cluster.metrics_history.gauge_values(
            "serve_queue_depth", window,
            where={"app": app, "deployment": deployment})
        return float(vals[-1]) if vals else 0.0

    def tick(self) -> List[Decision]:
        """One control pass. The world is re-derived from the controller and
        the metrics history every time — a restarted head resumes from the
        KV-restored app configs with no handoff."""
        import ray_tpu
        from ray_tpu.config import CONFIG
        from ray_tpu.util import fault_injection, telemetry

        fault_injection.fail_point("serve.autoscaler.decide")
        t0 = time.perf_counter()
        controller = self._controller()
        if controller is None:
            return []
        try:
            state = ray_tpu.get(controller.get_autoscale_state.remote(),
                                timeout=5.0)
        except Exception as e:  # noqa: BLE001 — controller RPC loss
            self._journal_event({"event": "state_rpc_error",
                                 "error": repr(e)}, reason="rpc_error")
            if self._warn.ready("state"):
                logger.warning("serve autoscaler could not read controller "
                               "state (retrying next tick): %r", e)
            return []
        self.ticks += 1
        self.policy.prune(state)
        now = time.monotonic()
        burning_rows, burning_slos = self._burning_names()
        decisions: List[Decision] = []
        with self._lock:
            self._targets = {k: dict(v) for k, v in state.items()}
            for key in [k for k in self._hinted if k not in state]:
                self._hinted.discard(key)
                self._clear_demand_hint(key)
        for key, row in state.items():
            app, deployment = row["app"], row["deployment"]
            burning = any(
                self._slo_matches(slo, b_row, app, deployment,
                                  row.get("route_prefix", ""),
                                  row.get("slo_names"))
                for (name, b_row), slo in zip(burning_rows.items(),
                                              burning_slos))
            queue_depth = self._queue_depth(app, deployment)
            queue_target = float(row.get("target_queue_depth") or
                                 CONFIG.serve_autoscale_queue_target)
            snap = DeploymentSnapshot(
                key=key, target=row["target"], running=row["running"],
                starting=row["starting"], draining=row["draining"],
                min_replicas=row["min_replicas"],
                max_replicas=row["max_replicas"],
                queue_depth=queue_depth, queue_target=queue_target,
                burning=burning, now=now)
            decision = self.policy.decide(snap)
            decisions.append(decision)
            with self._lock:
                self._targets[key].update(
                    queue_depth=queue_depth, burning=burning,
                    desired=decision.desired, reason=decision.reason)
            if decision.changed:
                self._apply(controller, app, deployment, decision, snap)
            self._handle_deficit(controller, app, deployment, row, snap)
        if telemetry.enabled() and any(d.changed for d in decisions):
            telemetry.event(
                "serve.autoscale.tick", "serve",
                changed=sum(1 for d in decisions if d.changed),
                deployments=len(decisions))
        # control-plane self-telemetry: full decide+commit pass wall time
        telemetry.get_histogram(
            "control_decision_seconds",
            "wall time of one control-loop decision pass, by loop",
            tag_keys=("loop",),
        ).observe(time.perf_counter() - t0, tags={"loop": "autoscaler"})
        return decisions

    def _apply(self, controller, app: str, deployment: str,
               decision: Decision, snap: DeploymentSnapshot) -> None:
        """Push one accepted decision to the controller; the reconcile loop's
        DRAINING choreography executes it. An RPC loss is journaled and the
        cooldown NOT burned, so the next tick retries."""
        import ray_tpu
        from ray_tpu.util import telemetry

        t0 = time.time_ns()
        try:
            with telemetry.span("serve.autoscale", "serve", app=app,
                                deployment=deployment, target=decision.target,
                                desired=decision.desired,
                                reason=decision.reason):
                applied = ray_tpu.get(controller.set_autoscale_target.remote(
                    app, deployment, decision.desired,
                    reason=decision.reason), timeout=5.0)
        except Exception as e:  # noqa: BLE001 — controller RPC loss
            self._journal_event(
                {"event": "scale_rpc_error", "key": decision.key,
                 "desired": decision.desired, "error": repr(e)},
                reason="rpc_error")
            if self._warn.ready("apply"):
                logger.warning("serve autoscaler scale RPC to %s failed "
                               "(will retry next tick): %r", decision.key, e)
            return
        if applied is None:
            # the deployment vanished between the state read and the apply
            # (delete raced the tick): nothing was scaled, journal it as such
            self._journal_event(
                {"event": "deployment_gone", "key": decision.key,
                 "desired": decision.desired}, reason="gone")
            return
        self.policy.commit(decision, snap.now)
        self._journal_event(
            {"event": "scale", "key": decision.key, "from": decision.target,
             "to": applied, "reason": decision.reason,
             "queue_depth": round(snap.queue_depth, 1),
             "burning": snap.burning, "latency_ms":
                 round((time.time_ns() - t0) / 1e6, 1)},
            reason=decision.reason)
        try:
            from ray_tpu.util import telemetry as _t

            _t.get_gauge(
                "serve_autoscale_target",
                "current autoscaler replica target per deployment",
                tag_keys=("app", "deployment")).set(
                float(applied), tags={"app": app, "deployment": deployment})
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the control loop down
        except Exception:
            pass
        logger.info("serve autoscale %s: %d -> %d (%s, queue_depth=%.1f)",
                    decision.key, decision.target, applied, decision.reason,
                    snap.queue_depth)

    # -- stuck scale-up: hand demand to the node autoscaler + retry elsewhere --
    def _handle_deficit(self, controller, app: str, deployment: str,
                        row: Dict[str, Any], snap: DeploymentSnapshot) -> None:
        key = snap.key
        if not self.policy.stuck_deficit(snap):
            if snap.running >= snap.target and key in self._hinted:
                with self._lock:
                    self._hinted.discard(key)
                self._clear_demand_hint(key)
            return
        with self._lock:
            first_time = key not in self._hinted
            self._hinted.add(key)
        deficit = snap.target - snap.running
        shape = dict(row.get("resource_shape") or {"CPU": 1.0})
        self._post_demand_hint(key, [shape] * deficit)
        if not first_time:
            return  # hint already posted; restart kicked once per episode
        self._journal_event(
            {"event": "scale_up_stuck", "key": key, "target": snap.target,
             "running": snap.running, "deficit": deficit,
             "hint_shape": shape}, reason="stuck")
        logger.warning(
            "serve autoscale %s stuck below target (%d/%d) past the startup "
            "timeout: posted node-autoscaler demand hint and restarting "
            "wedged STARTING replicas elsewhere", key, snap.running,
            snap.target)
        try:
            import ray_tpu

            ray_tpu.get(controller.restart_stuck_replicas.remote(
                app, deployment,
                older_than_s=self.policy.startup_timeout_s), timeout=5.0)
        except Exception as e:  # noqa: BLE001 — best-effort; reconcile retries
            if self._warn.ready("restart_stuck"):
                logger.warning("restart_stuck_replicas RPC for %s failed: %r",
                               key, e)

    @staticmethod
    def _post_demand_hint(key: str, shapes: List[Dict[str, float]]) -> None:
        try:
            from ray_tpu.autoscaler import autoscaler as node_autoscaler

            node_autoscaler.post_demand_hint(f"serve:{key}", shapes)
        # graftlint: allow[swallowed-exception] the node-autoscaler plane is optional; without it the hint has no consumer
        except Exception:
            pass

    @staticmethod
    def _clear_demand_hint(key: str) -> None:
        try:
            from ray_tpu.autoscaler import autoscaler as node_autoscaler

            node_autoscaler.clear_demand_hint(f"serve:{key}")
        # graftlint: allow[swallowed-exception] the node-autoscaler plane is optional; without it the hint has no consumer
        except Exception:
            pass


# ------------------------------------------------------------- head singleton

_singleton_lock = threading.Lock()
_loop: Optional[ServeAutoscalerLoop] = None


def ensure_serve_autoscaler() -> Optional[ServeAutoscalerLoop]:
    """Start (or restart) the head-side loop. Safe to call from any serve
    entry point and from the head-restart reattach path: no-op off the head
    (no in-process cluster), no-op when the loop is already live, and a loop
    bound to a DEAD cluster is replaced — the fresh loop re-derives every
    target from the controller's restored app configs."""
    global _loop
    from ray_tpu.core import global_state

    c = global_state.try_cluster()
    if c is None:
        return None
    with _singleton_lock:
        if _loop is not None and _loop.cluster is c and _loop.alive():
            return _loop
        if _loop is not None:
            _loop.stop()
        _loop = ServeAutoscalerLoop(c)
        return _loop


def get_serve_autoscaler() -> Optional[ServeAutoscalerLoop]:
    with _singleton_lock:
        return _loop


def shutdown_serve_autoscaler() -> None:
    """Stop the loop (serve.shutdown). The next ensure_ call starts fresh."""
    global _loop
    with _singleton_lock:
        loop, _loop = _loop, None
    if loop is not None:
        loop.stop()
