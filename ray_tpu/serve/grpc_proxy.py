"""gRPC ingress proxy.

Capability parity: reference python/ray/serve/_private/proxy.py:523 (gRPCProxy —
per-node ingress serving USER-DEFINED protobuf services next to deployment
handles). Two surfaces:

1. **User protobuf services** (reference parity): pass the generated
   ``add_XServicer_to_server`` functions via
   ``serve.start(grpc_options={"port": N, "grpc_servicer_functions": [...]})``.
   Each RPC method routes to the deployment method of the SAME name; the target
   application rides the call metadata key ``application`` (single running app =
   implicit default). The deployment receives the deserialized request message
   and returns the response message — typed end to end, no JSON.
2. A generic unary-unary service (`rayserve.Generic/Call`) carrying a JSON
   envelope {app, method, args, kwargs}, so any grpcio client can call any
   deployment without codegen. JSON (not pickle) is deliberate: the ingress
   deserializes untrusted network bytes.

Unary RPCs only (streaming gRPC ingress is not implemented).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.exceptions import BackPressureError

SERVICE = "rayserve.Generic"
METHOD = "Call"


class _RoutingServicer:
    """Stands in for a user's Servicer: every RPC method the generated
    ``add_XServicer_to_server`` looks up resolves to a router that forwards the
    request message to the deployment method of the same name."""

    def __init__(self, route):
        self._route = route

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)

        def handler(request, context):
            return self._route(method_name, request, context)

        return handler


class GrpcProxyActor:
    """Per-node gRPC ingress (reference gRPCProxy)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9000,
                 grpc_servicer_functions: Optional[List[Any]] = None):
        from concurrent.futures import ThreadPoolExecutor

        import grpc

        self.host = host
        self._handles: Dict[tuple, Any] = {}
        self._handles_lock = threading.Lock()

        def route(app: str, method: str, args, kwargs):
            key = (app, method)
            with self._handles_lock:
                handle = self._handles.get(key)
            from_cache = handle is not None
            if handle is None:
                from . import api

                handle = api.get_app_handle(app).options(method_name=method)
            result = handle.remote(*args, **kwargs).result()
            if not from_cache:
                # cache only after a successful call: a failing fresh handle must
                # not masquerade as a stale-cache entry in the retry logic below
                with self._handles_lock:
                    self._handles[key] = handle
            return result

        def set_retry_after(context, e: BackPressureError) -> None:
            """The gRPC analog of the HTTP Retry-After header: trailing
            metadata, clamped to a whole positive second."""
            try:
                context.set_trailing_metadata(
                    (("retry-after", str(max(1, int(e.retry_after_s)))),))
            # graftlint: allow[swallowed-exception] context already finalized: retry-after metadata is advisory
            except Exception:  # noqa: BLE001 — context already finalized
                pass

        def route_with_retry(app: str, method: str, args, kwargs):
            try:
                return route(app, method, args, kwargs)
            except BackPressureError:
                raise  # shed by admission control: a stale-cache retry would
                # just shed again — surface the typed rejection immediately
            except Exception:
                with self._handles_lock:
                    was_cached = self._handles.pop((app, method), None) is not None
                if not was_cached:
                    raise  # fresh handle: a user-code error, never retried
                # the CACHED handle may be stale (app deleted/redeployed):
                # retry once against a freshly resolved one. User methods may
                # run twice only in the stale-cache window — same contract as
                # the reference proxy's retry-on-unavailable-replica.
                return route(app, method, args, kwargs)

        def call(request: bytes, context) -> bytes:
            try:
                req = json.loads(request)
                app = req["app"]
                method = req.get("method") or "__call__"
                result = route_with_retry(app, method, req.get("args") or [],
                                          req.get("kwargs") or {})
                return json.dumps({"ok": True, "result": result}).encode()
            except BackPressureError as e:
                # typed shed: retry_after_s in the envelope AND as metadata
                set_retry_after(context, e)
                return json.dumps({"ok": False, "error": repr(e), "shed": True,
                                   "retry_after_s": e.retry_after_s}).encode()
            except Exception as e:  # noqa: BLE001
                return json.dumps({"ok": False, "error": repr(e)}).encode()

        def route_typed(method_name: str, request, context):
            """User-proto RPC -> deployment method of the same name. The app
            comes from call metadata ('application'); with exactly one running
            app it is implicit (reference proxy.py:523 routing)."""
            import grpc as _grpc

            app = None
            for k, v in context.invocation_metadata():
                if k == "application":
                    app = v
            if app is None:
                from . import api

                try:
                    apps = sorted(api.status())
                except Exception as e:  # noqa: BLE001
                    context.abort(_grpc.StatusCode.INTERNAL, repr(e))
                if len(apps) != 1:
                    # abort OUTSIDE the routing try: its control-flow exception
                    # must not be re-wrapped as INTERNAL
                    context.abort(
                        _grpc.StatusCode.INVALID_ARGUMENT,
                        f"metadata 'application' required ({len(apps)} apps "
                        "running)")
                app = apps[0]
            try:
                return route_with_retry(app, method_name, (request,), {})
            except BackPressureError as e:
                set_retry_after(context, e)
                context.abort(_grpc.StatusCode.RESOURCE_EXHAUSTED, repr(e))
            except Exception as e:  # noqa: BLE001 — surface as gRPC status
                context.abort(_grpc.StatusCode.INTERNAL, repr(e))

        rpc = grpc.unary_unary_rpc_method_handler(
            call, request_deserializer=None, response_serializer=None)
        handler = grpc.method_handlers_generic_handler(SERVICE, {METHOD: rpc})
        self._server = grpc.server(ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((handler,))
        # user-defined protobuf services (reference grpc_servicer_functions):
        # each generated add_XServicer_to_server registers its method table
        # against a router that forwards typed messages to deployments
        for add_fn in grpc_servicer_functions or ():
            add_fn(_RoutingServicer(route_typed), self._server)
        from ray_tpu.config import CONFIG

        if CONFIG.serve_ingress_tls:
            from ray_tpu.core.tls_utils import ingress_grpc_credentials

            self.port = self._server.add_secure_port(
                f"{host}:{port}", ingress_grpc_credentials())
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"gRPC proxy failed to bind {host}:{port}")
        self._server.start()

    def ready(self) -> int:
        return self.port

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def grpc_call(address: str, app: str, *args, method: Optional[str] = None, **kwargs) -> Any:
    """Client helper: one unary call to a serve deployment over the gRPC proxy.

    Payloads are JSON — args/kwargs/results must be JSON-serializable (the
    ingress will not unpickle untrusted bytes)."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(f"/{SERVICE}/{METHOD}")
        payload = json.dumps(
            {"app": app, "method": method, "args": list(args), "kwargs": kwargs}).encode()
        resp = json.loads(fn(payload, timeout=60.0))
    if not resp["ok"]:
        raise RuntimeError(f"serve grpc call failed: {resp['error']}")
    return resp["result"]


_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 9000,
                     grpc_servicer_functions: Optional[List[Any]] = None):
    """Get-or-create the gRPC ingress actor; returns (handle, bound_port).

    grpc_servicer_functions: generated ``add_XServicer_to_server`` functions
    for user protobuf services (must be importable by workers — generated
    modules are). If a proxy already exists, its existing bound port is
    returned and all arguments are ignored (one ingress per cluster, like the
    HTTP proxy's get-or-create)."""
    try:
        proxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
    except ValueError:
        cls = ray_tpu.remote(num_cpus=0.1, name=_GRPC_PROXY_NAME,
                             lifetime="detached")(GrpcProxyActor)
        proxy = cls.remote(host, port, grpc_servicer_functions)
    return proxy, ray_tpu.get(proxy.ready.remote())
