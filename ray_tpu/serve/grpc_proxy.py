"""gRPC ingress proxy.

Capability parity: reference python/ray/serve/_private/proxy.py:523 (gRPCProxy —
per-node grpc.aio ingress routing to deployment handles). Design difference: the
reference requires user-compiled protos; here one generic unary-unary service
(`rayserve.Generic/Call`) carries a JSON envelope {app, method, args, kwargs},
so any client with grpcio can call any deployment without codegen. JSON (not
pickle) is deliberate: the ingress deserializes untrusted network bytes.
`serve.start(grpc_options={"port": N})` brings it up; `grpc_call(address, app,
...)` is the matching client helper.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional

import ray_tpu

SERVICE = "rayserve.Generic"
METHOD = "Call"


class GrpcProxyActor:
    """Per-node gRPC ingress (reference gRPCProxy)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        from concurrent.futures import ThreadPoolExecutor

        import grpc

        self.host = host
        self._handles: Dict[tuple, Any] = {}
        self._handles_lock = threading.Lock()

        def route(app: str, method: str, args, kwargs):
            key = (app, method)
            with self._handles_lock:
                handle = self._handles.get(key)
            from_cache = handle is not None
            if handle is None:
                from . import api

                handle = api.get_app_handle(app).options(method_name=method)
            result = handle.remote(*args, **kwargs).result()
            if not from_cache:
                # cache only after a successful call: a failing fresh handle must
                # not masquerade as a stale-cache entry in the retry logic below
                with self._handles_lock:
                    self._handles[key] = handle
            return result

        def call(request: bytes, context) -> bytes:
            try:
                req = json.loads(request)
                app = req["app"]
                method = req.get("method") or "__call__"
                args = req.get("args") or []
                kwargs = req.get("kwargs") or {}
                try:
                    result = route(app, method, args, kwargs)
                except Exception:
                    with self._handles_lock:
                        was_cached = self._handles.pop((app, method), None) is not None
                    if not was_cached:
                        raise  # fresh handle: a user-code error, never retried
                    # the CACHED handle may be stale (app deleted/redeployed):
                    # retry once against a freshly resolved one. User methods may
                    # run twice only in the stale-cache window — same contract as
                    # the reference proxy's retry-on-unavailable-replica.
                    result = route(app, method, args, kwargs)
                return json.dumps({"ok": True, "result": result}).encode()
            except Exception as e:  # noqa: BLE001
                return json.dumps({"ok": False, "error": repr(e)}).encode()

        rpc = grpc.unary_unary_rpc_method_handler(
            call, request_deserializer=None, response_serializer=None)
        handler = grpc.method_handlers_generic_handler(SERVICE, {METHOD: rpc})
        self._server = grpc.server(ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"gRPC proxy failed to bind {host}:{port}")
        self._server.start()

    def ready(self) -> int:
        return self.port

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def grpc_call(address: str, app: str, *args, method: Optional[str] = None, **kwargs) -> Any:
    """Client helper: one unary call to a serve deployment over the gRPC proxy.

    Payloads are JSON — args/kwargs/results must be JSON-serializable (the
    ingress will not unpickle untrusted bytes)."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(f"/{SERVICE}/{METHOD}")
        payload = json.dumps(
            {"app": app, "method": method, "args": list(args), "kwargs": kwargs}).encode()
        resp = json.loads(fn(payload, timeout=60.0))
    if not resp["ok"]:
        raise RuntimeError(f"serve grpc call failed: {resp['error']}")
    return resp["result"]


_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 9000):
    """Get-or-create the gRPC ingress actor; returns (handle, bound_port).

    If a proxy already exists, its existing bound port is returned and the
    host/port arguments are ignored (one ingress per cluster, like the HTTP
    proxy's get-or-create)."""
    try:
        proxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
    except ValueError:
        cls = ray_tpu.remote(num_cpus=0.1, name=_GRPC_PROXY_NAME,
                             lifetime="detached")(GrpcProxyActor)
        proxy = cls.remote(host, port)
    return proxy, ray_tpu.get(proxy.ready.remote())
