"""Replica: the actor hosting one copy of a deployment's user code.

Capability parity: reference python/ray/serve/_private/replica.py (1,903 LoC) —
user callable host, health check, reconfigure via user_config, graceful
shutdown + draining, per-replica request accounting for admission control.
Control-plane methods (health, drain, fault arming) run on their own
"control" concurrency group so a replica saturated with user requests still
answers the controller promptly.
"""
from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core.actor import method as _actor_method
from ray_tpu.core.exceptions import ReplicaUnavailableError
from ray_tpu.util import fault_injection


class Replica:
    def __init__(
        self,
        deployment_name: str,
        serialized_init: Dict[str, Any],
        user_config: Optional[Dict[str, Any]] = None,
        app_name: str = "",
        max_ongoing_requests: int = 0,
    ):
        self.deployment_name = deployment_name
        self.app_name = app_name
        cls_or_fn = serialized_init["target"]

        def decode(v):
            from .api import _HandleMarker
            from .handle import DeploymentHandle

            if isinstance(v, _HandleMarker):
                return DeploymentHandle(v.app_name, v.deployment_name)
            return v

        args = tuple(decode(a) for a in serialized_init.get("args", ()))
        kwargs = {k: decode(v) for k, v in serialized_init.get("kwargs", {}).items()}
        if inspect.isclass(cls_or_fn):
            self.callable = cls_or_fn(*args, **kwargs)
        else:
            self.callable = cls_or_fn
        self._num_served = 0
        self._started_at = time.time()
        self._lock = threading.Lock()
        self._ongoing = 0  # requests currently executing (streams: until closed)
        self._draining = False
        self._max_ongoing = max_ongoing_requests
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request accounting ------------------------------------------------------
    def _begin_request(self) -> None:
        with self._lock:
            if self._draining:
                # a send that raced the DRAINING transition: bounce it so the
                # caller's retry plane resends to a live replica instead of
                # riding this one into the kill
                raise ReplicaUnavailableError(
                    self.app_name, self.deployment_name,
                    replica=self.deployment_name, reason="replica is draining")
            self._ongoing += 1
            self._num_served += 1

    def _end_request(self) -> None:
        with self._lock:
            self._ongoing = max(0, self._ongoing - 1)

    def _wrap_stream(self, gen):
        """Streaming responses stay 'ongoing' until the generator is exhausted
        or closed — draining must wait for the last chunk, not the first."""
        def run():
            try:
                yield from gen
            finally:
                self._end_request()
        return run()

    async def _wrap_async_stream(self, agen):
        try:
            async for item in agen:
                yield item
        finally:
            self._end_request()

    # -- request path ----------------------------------------------------------
    def handle_request(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        fault_injection.fail_point(
            "serve.replica.request", app=self.app_name,
            deployment=self.deployment_name, method=method_name or "__call__")
        self._begin_request()
        try:
            from ray_tpu.util import tracing

            if tracing.is_tracing_enabled():
                # a named replica span under the worker's task:: span: the trace
                # tree shows WHICH deployment served the request, and engine /
                # data-plane telemetry recorded inside inherits the trace id
                with tracing.span(f"replica.{self.deployment_name}",
                                  {"method": method_name or "__call__"}):
                    out = self._handle_request_inner(method_name, args, kwargs)
            else:
                out = self._handle_request_inner(method_name, args, kwargs)
        except BaseException:
            self._end_request()
            raise
        if inspect.isgenerator(out):
            return self._wrap_stream(out)
        if inspect.isasyncgen(out):
            return self._wrap_async_stream(out)
        self._end_request()
        return out

    def _handle_request_inner(self, method_name: str, args: tuple,
                              kwargs: dict) -> Any:
        from .multiplex import MULTIPLEX_KWARG, _set_multiplexed_model_id

        model_id = kwargs.pop(MULTIPLEX_KWARG, None)
        # always (re)set: a request without a model id must not inherit the previous
        # request's id from this thread's context
        _set_multiplexed_model_id(model_id or "")
        if method_name == "__http__":
            # Proxy path: full request dict {path, method, query, body}. Ingress classes
            # that define handle_http get it verbatim; plain callables get just the body
            # (reference: replica ASGI wrapping vs plain-handle calls).
            request = args[0]
            fn = getattr(self.callable, "handle_http", None)
            if fn is not None:
                return fn(request)
            method_name, args = "__call__", (request["body"],)
        if method_name in ("__call__", None):
            target = self.callable if callable(self.callable) else None
            if target is None:
                raise AttributeError(f"deployment {self.deployment_name} is not callable")
            return self._maybe_await(target(*args, **kwargs))
        return self._maybe_await(getattr(self.callable, method_name)(*args, **kwargs))

    @staticmethod
    def _maybe_await(out: Any) -> Any:
        """async def deployment methods: run the coroutine to completion on this
        request's thread (replicas are threaded actors, so concurrent requests
        still overlap; reference replica.py async user callables). Async
        generators pass through — the streaming path drives them."""
        if inspect.iscoroutine(out):
            import asyncio

            return asyncio.run(out)
        return out

    # -- control plane (own concurrency group: never starved by user requests) --
    @_actor_method(concurrency_group="control")
    def check_health(self) -> bool:
        fault_injection.fail_point(
            "serve.replica.health", app=self.app_name,
            deployment=self.deployment_name)
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    @_actor_method(concurrency_group="control")
    def drain(self) -> int:
        """Enter DRAINING: stop accepting new requests (racing sends bounce
        with ReplicaUnavailableError so callers retry elsewhere) and report
        how many are still in flight. The controller polls until 0, then
        kills — zero dropped requests on a routine scale-down."""
        with self._lock:
            self._draining = True
            return self._ongoing

    @_actor_method(concurrency_group="control")
    def num_inflight(self) -> int:
        with self._lock:
            return self._ongoing

    @_actor_method(concurrency_group="control")
    def _arm_fault(self, site: str, mode: str = "error", prob: float = 1.0,
                   count: Optional[int] = None, delay_s: float = 0.0,
                   seed: Optional[int] = None) -> bool:
        """ChaosController hook: arm a fail point in THIS replica process."""
        fault_injection.arm(site, mode, prob, count, delay_s, seed)
        return True

    @_actor_method(concurrency_group="control")
    def _disarm_fault(self, site: Optional[str] = None) -> bool:
        fault_injection.disarm(site)
        return True

    @_actor_method(concurrency_group="control")
    def reconfigure(self, user_config: Dict[str, Any]) -> None:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    @_actor_method(concurrency_group="control")
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            ongoing = self._ongoing
            served = self._num_served
        # max_ongoing is ENFORCED by the actor's max_concurrency (set by the
        # controller); reported here so operators can read ongoing vs cap
        return {"num_served": served, "num_ongoing": ongoing,
                "max_ongoing": self._max_ongoing,
                "draining": self._draining,
                "uptime_s": time.time() - self._started_at}

    @_actor_method(concurrency_group="control")
    def prepare_shutdown(self) -> None:
        fn = getattr(self.callable, "__del__", None)
        # graceful user shutdown hook (reference: replica graceful_shutdown path)
        hook = getattr(self.callable, "shutdown", None)
        if hook is not None:
            try:
                hook()
            # graftlint: allow[swallowed-exception] callback isolation: a throwing subscriber must not break the caller
            except Exception:
                pass
