"""Replica: the actor hosting one copy of a deployment's user code.

Capability parity: reference python/ray/serve/_private/replica.py (1,903 LoC) —
user callable host, health check, reconfigure via user_config, graceful shutdown.
"""
from __future__ import annotations

import inspect
import time
from typing import Any, Dict, Optional


class Replica:
    def __init__(
        self,
        deployment_name: str,
        serialized_init: Dict[str, Any],
        user_config: Optional[Dict[str, Any]] = None,
    ):
        self.deployment_name = deployment_name
        cls_or_fn = serialized_init["target"]

        def decode(v):
            from .api import _HandleMarker
            from .handle import DeploymentHandle

            if isinstance(v, _HandleMarker):
                return DeploymentHandle(v.app_name, v.deployment_name)
            return v

        args = tuple(decode(a) for a in serialized_init.get("args", ()))
        kwargs = {k: decode(v) for k, v in serialized_init.get("kwargs", {}).items()}
        if inspect.isclass(cls_or_fn):
            self.callable = cls_or_fn(*args, **kwargs)
        else:
            self.callable = cls_or_fn
        self._num_served = 0
        self._started_at = time.time()
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path ----------------------------------------------------------
    def handle_request(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        self._num_served += 1
        from ray_tpu.util import tracing

        if tracing.is_tracing_enabled():
            # a named replica span under the worker's task:: span: the trace
            # tree shows WHICH deployment served the request, and engine /
            # data-plane telemetry recorded inside inherits the trace id
            with tracing.span(f"replica.{self.deployment_name}",
                              {"method": method_name or "__call__"}):
                return self._handle_request_inner(method_name, args, kwargs)
        return self._handle_request_inner(method_name, args, kwargs)

    def _handle_request_inner(self, method_name: str, args: tuple,
                              kwargs: dict) -> Any:
        from .multiplex import MULTIPLEX_KWARG, _set_multiplexed_model_id

        model_id = kwargs.pop(MULTIPLEX_KWARG, None)
        # always (re)set: a request without a model id must not inherit the previous
        # request's id from this thread's context
        _set_multiplexed_model_id(model_id or "")
        if method_name == "__http__":
            # Proxy path: full request dict {path, method, query, body}. Ingress classes
            # that define handle_http get it verbatim; plain callables get just the body
            # (reference: replica ASGI wrapping vs plain-handle calls).
            request = args[0]
            fn = getattr(self.callable, "handle_http", None)
            if fn is not None:
                return fn(request)
            method_name, args = "__call__", (request["body"],)
        if method_name in ("__call__", None):
            target = self.callable if callable(self.callable) else None
            if target is None:
                raise AttributeError(f"deployment {self.deployment_name} is not callable")
            return self._maybe_await(target(*args, **kwargs))
        return self._maybe_await(getattr(self.callable, method_name)(*args, **kwargs))

    @staticmethod
    def _maybe_await(out: Any) -> Any:
        """async def deployment methods: run the coroutine to completion on this
        request's thread (replicas are threaded actors, so concurrent requests
        still overlap; reference replica.py async user callables). Async
        generators pass through — the streaming path drives them."""
        if inspect.iscoroutine(out):
            import asyncio

            return asyncio.run(out)
        return out

    # -- control plane ---------------------------------------------------------
    def check_health(self) -> bool:
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config: Dict[str, Any]) -> None:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def stats(self) -> Dict[str, Any]:
        return {"num_served": self._num_served, "uptime_s": time.time() - self._started_at}

    def prepare_shutdown(self) -> None:
        fn = getattr(self.callable, "__del__", None)
        # graceful user shutdown hook (reference: replica graceful_shutdown path)
        hook = getattr(self.callable, "shutdown", None)
        if hook is not None:
            try:
                hook()
            except Exception:
                pass
