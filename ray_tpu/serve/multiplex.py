"""Model multiplexing: many models share a replica pool with LRU residency.

Capability parity: reference python/ray/serve/multiplex.py (@serve.multiplexed
+ serve.get_multiplexed_model_id) — a replica lazily loads models through the
decorated loader, keeps at most max_num_models_per_replica resident (LRU
eviction), and handles route model-affine: a request for model M prefers a
replica that already holds M (reference: router's multiplexed replica ranking).
"""
from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")

MULTIPLEX_KWARG = "__serve_multiplexed_model_id"


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request was routed for."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    return _current_model_id.set(model_id)


class _MultiplexWrapper:
    """Per-replica LRU of loaded models around the user's loader function."""

    def __init__(self, loader: Callable, max_num_models: int, owner=None):
        self._loader = loader
        self._owner = owner  # instance for bound-method loaders
        self.max_num_models = max_num_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        import weakref

        self._bound_map = weakref.WeakKeyDictionary()  # instance -> bound wrapper

    def __get__(self, obj, objtype=None):
        # method decorator support: one bound wrapper (and LRU) per instance
        if obj is None:
            return self
        try:
            bound = self._bound_map.get(obj)
            if bound is None:
                bound = _MultiplexWrapper(self._loader, self.max_num_models, owner=obj)
                self._bound_map[obj] = bound
            return bound
        except TypeError:  # non-weakref-able instance: uncached bind
            return _MultiplexWrapper(self._loader, self.max_num_models, owner=obj)

    def __call__(self, model_id: Optional[str] = None) -> Any:
        if model_id is None:
            model_id = get_multiplexed_model_id()
        if not model_id:
            raise ValueError(
                "no multiplexed model id: pass one explicitly or set "
                "handle.options(multiplexed_model_id=...) on the caller")
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # load outside the lock (loads can be slow); racing loads of the same id
        # resolve by last-writer-wins, matching the reference's per-id lock window
        args = (self._owner, model_id) if self._owner is not None else (model_id,)
        model = self._loader(*args)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self.max_num_models:
                evicted_id, evicted = self._models.popitem(last=False)
                del_fn = getattr(evicted, "__del__", None)
                if callable(del_fn):
                    try:
                        del_fn()
                    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                    except Exception:
                        pass
        return model

    def loaded_model_ids(self):
        with self._lock:
            return list(self._models)

    # cloudpickle support: the LRU and lock are per-process state, only the loader
    # and the capacity travel with the deployment class
    def __getstate__(self):
        return {"loader": self._loader, "max_num_models": self.max_num_models}

    def __setstate__(self, state):
        self.__init__(state["loader"], state["max_num_models"])


def multiplexed(func: Optional[Callable] = None, *, max_num_models_per_replica: int = 3):
    """Decorator for a model loader fn/method (reference @serve.multiplexed)."""

    def wrap(f):
        return _MultiplexWrapper(f, max_num_models_per_replica)

    if func is not None:
        return wrap(func)
    return wrap
