"""ray_tpu.serve: scalable model serving over the actor runtime.

Capability parity: reference python/ray/serve/ — @serve.deployment / serve.run
(api.py:322,691), ServeController reconciliation (controller.py:88), replica state
machine + rolling updates (deployment_state.py), power-of-two-choices handle router
(request_router/pow_2_router.py:27), aiohttp ingress proxy (proxy.py), @serve.batch
dynamic batching (batching.py), request-rate autoscaling (autoscaling_policy.py).
"""
from .api import (  # noqa: F401
    delete,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from .batching import batch  # noqa: F401
from .multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
from .schema import apply_config, apply_config_file  # noqa: F401
from .config import AutoscalingConfig, DeploymentConfig  # noqa: F401
from .deployment import Application, Deployment, deployment  # noqa: F401
from .handle import DeploymentHandle, DeploymentResponse  # noqa: F401
from .asgi import ingress  # noqa: F401

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "run",
    "start",
    "delete",
    "status",
    "shutdown",
    "get_app_handle",
    "get_deployment_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "AutoscalingConfig",
    "DeploymentConfig",
    "batch",
    "ingress",
    "multiplexed",
    "get_multiplexed_model_id",
    "apply_config",
    "apply_config_file",
]
