"""Serve configs (reference python/ray/serve/config.py, schema.py)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Two autoscaling modes share this config:

    - ``mode="ongoing"`` (reference serve/config.py AutoscalingConfig):
      request-rate driven, reconciled inside the controller from
      handle-pushed ongoing-request counts (``target_ongoing_requests`` +
      up/downscale delays).
    - ``mode="slo"``: the head-side closed loop (serve/autoscaler.py) drives
      the target from ``subscribe_slo()`` burn-rate transitions and the live
      ``serve_queue_depth`` gauges. ``target_queue_depth`` is the desired
      in-flight per replica (None = RAY_TPU_SERVE_AUTOSCALE_QUEUE_TARGET);
      ``slo_names`` pins which registered SLOs drive this deployment (None =
      any serve SLO whose ``where`` tags match the app/deployment/route).
      Hysteresis/cooldowns come from the RAY_TPU_SERVE_AUTOSCALE_* knobs.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 1.0
    mode: str = "ongoing"
    target_queue_depth: Optional[float] = None
    slo_names: Optional[list] = None

    def __post_init__(self):
        if self.mode not in ("ongoing", "slo"):
            raise ValueError(
                f"autoscaling mode must be 'ongoing' or 'slo', got {self.mode!r}")

    @classmethod
    def for_slo(
        cls,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        slo_names: Optional[list] = None,
        target_queue_depth: Optional[float] = None,
    ) -> "AutoscalingConfig":
        """Closed-loop config: scale off SLO burn and/or live queue depth.

        ``slo_names`` pins the deployment to specific registered SLOs (e.g. a
        TTFT latency SLO for a prefill pool); ``target_queue_depth`` sets the
        desired in-flight per replica (e.g. decode pools sized off backlog).
        """
        return cls(
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            mode="slo",
            slo_names=slo_names,
            target_queue_depth=target_queue_depth,
        )


def _flag(name: str):
    from ray_tpu.config import flag

    return flag(name)


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: Optional[int] = 1
    max_ongoing_requests: int = dataclasses.field(
        default_factory=lambda: _flag("serve_max_ongoing_requests"))
    # queue cap beyond replica capacity (max_ongoing x replicas): excess
    # handle calls shed with BackPressureError / 503 + Retry-After instead of
    # queueing into latency collapse. -1 = unbounded (never shed).
    max_queued_requests: int = dataclasses.field(
        default_factory=lambda: _flag("serve_max_queued_requests"))
    # replica-death/unavailable failures resend the request to a DIFFERENT
    # replica (bounded exponential backoff). Set False for non-idempotent
    # methods whose double execution is worse than a surfaced error.
    retryable: bool = True
    # grace a DRAINING replica gets to finish in-flight requests on
    # scale-down/rolling update/shutdown before it is killed anyway
    drain_timeout_s: float = dataclasses.field(
        default_factory=lambda: _flag("serve_drain_timeout_s"))
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = dataclasses.field(
        default_factory=lambda: _flag("serve_health_check_period_s"))
    health_check_timeout_s: float = dataclasses.field(
        default_factory=lambda: _flag("serve_health_check_timeout_s"))
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    user_config: Optional[Dict[str, Any]] = None
    version: Optional[str] = None
    # replica->node packing (reference _private/deployment_scheduler.py):
    # "PACK" fills nodes in turn (compact, frees whole nodes for downscaling);
    # "SPREAD" balances replicas across nodes (availability)
    placement_strategy: str = "PACK"
