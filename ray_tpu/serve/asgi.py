"""ASGI ingress: host any ASGI app (FastAPI, Starlette, raw) in a deployment.

Capability parity: reference python/ray/serve/_private/replica.py:72
(ASGIAppReplicaWrapper) + serve.ingress (python/ray/serve/api.py) — the proxy's
request dict is translated into an ASGI HTTP scope, the app is driven on an
event loop, and the collected status/headers/body travel back through the
handle as a raw-response marker the proxy unwraps verbatim.

The image ships no FastAPI; anything speaking the ASGI 3.0 callable protocol
(`await app(scope, receive, send)`) works, which is exactly what FastAPI/
Starlette produce.
"""
from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, List

RAW_RESPONSE_KEY = "__serve_raw_http__"


def make_raw_response(status: int, headers: List, body: bytes) -> Dict[str, Any]:
    return {RAW_RESPONSE_KEY: True, "status": status,
            "headers": [(k.decode() if isinstance(k, bytes) else k,
                         v.decode() if isinstance(v, bytes) else v)
                        for k, v in headers],
            "body": body}


def _scope_from_request(request: Dict[str, Any]) -> Dict[str, Any]:
    query = "&".join(f"{k}={v}" for k, v in (request.get("query") or {}).items())
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.get("method", "GET"),
        "scheme": "http",
        "path": request.get("path", "/"),
        "raw_path": request.get("path", "/").encode(),
        "query_string": query.encode(),
        "root_path": "",
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (request.get("headers") or {}).items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 80),
    }


def _body_bytes(request: Dict[str, Any]) -> bytes:
    body = request.get("body")
    if body is None:
        return b""
    if isinstance(body, bytes):
        return body
    if isinstance(body, str):
        return body.encode()
    return json.dumps(body).encode()


async def _run_asgi(app, scope: Dict[str, Any], body: bytes) -> Dict[str, Any]:
    received = False
    messages: List[Dict[str, Any]] = []

    async def receive():
        nonlocal received
        if received:
            await asyncio.sleep(3600)  # app awaiting disconnect; never resolves
        received = True
        return {"type": "http.request", "body": body, "more_body": False}

    async def send(message):
        messages.append(message)

    await app(scope, receive, send)
    status, headers, out = 500, [], b""
    for m in messages:
        if m["type"] == "http.response.start":
            status = m["status"]
            headers = list(m.get("headers") or [])
        elif m["type"] == "http.response.body":
            out += m.get("body", b"")
    return make_raw_response(status, headers, out)


class ASGIAppWrapper:
    """Mixes an ASGI app into a deployment class (reference
    ASGIAppReplicaWrapper): Serve's __http__ path drives the app."""

    _asgi_app = None  # set by ingress()

    def handle_http(self, request: Dict[str, Any]) -> Dict[str, Any]:
        scope = _scope_from_request(request)
        return asyncio.run(_run_asgi(self._asgi_app, scope, _body_bytes(request)))


def ingress(app):
    """Class decorator: serve requests for this deployment through an ASGI app.

        app = FastAPI()

        @serve.deployment
        @serve.ingress(app)
        class Ingress:
            @app.get("/hello")
            def hello(self):
                return "hi"

    The decorated class gains handle_http (driving the app); FastAPI-style
    bound routes keep working because FastAPI resolves `self` through its own
    dependency injection when routes are defined on the class. Raw ASGI apps
    ignore the instance entirely.
    """

    def deco(cls):
        # staticmethod: a plain-function app must not be bound as a method when
        # accessed through the instance (FastAPI apps are instances; unaffected)
        return type(cls.__name__, (cls, ASGIAppWrapper),
                    {"_asgi_app": staticmethod(app)})

    return deco
