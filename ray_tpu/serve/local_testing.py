"""Local testing mode: run a Serve application graph in-process, no cluster.

Capability parity: reference python/ray/serve/_private/local_testing_mode.py —
`serve.run(app, _local_testing_mode=True)` instantiates every deployment in the
driver process and returns a handle whose .remote() executes the user callable
synchronously on a thread, so unit tests need no controller/proxy/replica actors.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Dict

from .deployment import Application


class LocalDeploymentResponse:
    """Mirrors DeploymentResponse: .result(timeout_s) on an in-process future."""

    def __init__(self, future: concurrent.futures.Future):
        self._future = future

    def result(self, timeout_s: float = None) -> Any:
        return self._future.result(timeout=timeout_s)


class LocalDeploymentHandle:
    """Mirrors DeploymentHandle for one in-process deployment instance."""

    def __init__(self, instance: Any, method_name: str = "__call__"):
        self._instance = instance
        self._method_name = method_name
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)

    def options(self, method_name: str = None, **_compat) -> "LocalDeploymentHandle":
        h = LocalDeploymentHandle(self._instance, method_name or self._method_name)
        h._pool = self._pool
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        target = self._instance
        if self._method_name != "__call__":
            fn = getattr(target, self._method_name)
        elif callable(target) and not isinstance(target, type):
            fn = target
        else:
            fn = target.__call__

        def call():
            import asyncio
            import inspect

            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                return asyncio.run(out)
            return out

        return LocalDeploymentResponse(self._pool.submit(call))


def run_local(target: Application) -> LocalDeploymentHandle:
    """Instantiate the bound graph bottom-up in this process (local testing mode)."""
    instances: Dict[str, Any] = {}
    handles: Dict[str, LocalDeploymentHandle] = {}
    lock = threading.Lock()

    def build(app: Application) -> LocalDeploymentHandle:
        name = app.deployment.name
        with lock:
            if name in handles:
                return handles[name]
        args = tuple(build(a) if isinstance(a, Application) else a for a in app.args)
        kwargs = {k: build(v) if isinstance(v, Application) else v for k, v in app.kwargs.items()}
        tgt = app.deployment._target
        instance = tgt(*args, **kwargs) if isinstance(tgt, type) else tgt
        if not isinstance(tgt, type) and (args or kwargs):
            # function deployment bound with args: partially apply them
            import functools

            instance = functools.partial(tgt, *args, **kwargs)
        h = LocalDeploymentHandle(instance)
        with lock:
            instances[name] = instance
            handles[name] = h
        return h

    return build(target)
