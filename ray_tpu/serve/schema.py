"""Declarative Serve config (reference python/ray/serve/schema.py + `serve deploy`).

Config shape (YAML/JSON/dict):

    applications:
      - name: my-app
        route_prefix: /api
        import_path: my_module:app        # module attr holding an Application
                                          # or a builder callable returning one
        args: {}                          # kwargs for a builder import_path
        deployments:                      # per-deployment overrides
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 16
            user_config: {...}

`apply_config` deploys every listed application (reference ServeDeploySchema →
controller deploy_apps).
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from .deployment import Application


def _load_target(import_path: str, args: Optional[Dict[str, Any]] = None) -> Application:
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(f"import_path must be 'module:attr', got {import_path!r}")
    mod = importlib.import_module(module_name)
    target = getattr(mod, attr)
    if isinstance(target, Application):
        if args:
            raise ValueError(f"{import_path} is an Application; args need a builder")
        return target
    if callable(target):
        app = target(**(args or {}))
        if not isinstance(app, Application):
            raise TypeError(f"builder {import_path} must return an Application")
        return app
    raise TypeError(f"{import_path} is neither an Application nor a builder")


def _apply_overrides(app: Application, overrides: List[Dict[str, Any]]) -> Application:
    """Rebind the graph with per-deployment option overrides (by deployment name)."""
    if not overrides:
        return app
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"} for o in overrides}

    def rebind(a: Application) -> Application:
        new_args = tuple(rebind(x) if isinstance(x, Application) else x for x in a.args)
        new_kwargs = {k: rebind(v) if isinstance(v, Application) else v
                      for k, v in a.kwargs.items()}
        d = a.deployment
        if d.name in by_name:
            d = d.options(**by_name[d.name])
        return Application(d, new_args, new_kwargs)

    return rebind(app)


def _deployment_names(app: Application) -> List[str]:
    collected: List[Application] = []
    app._collect(collected)
    return [a.deployment.name for a in collected]


def apply_config(config: Dict[str, Any]) -> List[str]:
    """Declaratively deploy the config (reference ServeDeploySchema semantics):
    every listed application is deployed/updated and any OTHER currently-running
    app is deleted — the config is the full desired state. Returns app names."""
    from . import api

    if not isinstance(config, dict) or not isinstance(config.get("applications"), list):
        raise ValueError("serve config must be a dict with an 'applications' list")

    apps = config["applications"]
    prefixes: Dict[str, str] = {}
    for app_cfg in apps:
        prefix = app_cfg.get("route_prefix", "/")
        other = prefixes.get(prefix)
        if other is not None:
            raise ValueError(
                f"applications {other!r} and {app_cfg.get('name', 'default')!r} both "
                f"use route_prefix {prefix!r}; routes must be unique")
        prefixes[prefix] = app_cfg.get("name", "default")

    deployed = []
    for app_cfg in apps:
        name = app_cfg.get("name", "default")
        app = _load_target(app_cfg["import_path"], app_cfg.get("args"))
        overrides = app_cfg.get("deployments", [])
        known = set(_deployment_names(app))
        unknown = [o["name"] for o in overrides if o["name"] not in known]
        if unknown:
            raise ValueError(
                f"app {name!r}: deployment overrides {unknown} match no deployment "
                f"in the graph (have: {sorted(known)})")
        app = _apply_overrides(app, overrides)
        api.run(app, name=name, route_prefix=app_cfg.get("route_prefix", "/"))
        deployed.append(name)

    # declarative: remove apps not in the config
    for existing in list(api.status()):
        if existing not in deployed:
            api.delete(existing)
    return deployed


def apply_config_file(path: str) -> List[str]:
    import json

    with open(path) as f:
        text = f.read()
    try:
        config = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml

            config = yaml.safe_load(text)
        except ImportError as e:
            raise ValueError(f"{path} is not JSON and pyyaml is unavailable") from e
    if not isinstance(config, dict):
        raise ValueError(f"{path}: serve config must parse to a mapping, "
                         f"got {type(config).__name__}")
    return apply_config(config)
