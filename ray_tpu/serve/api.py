"""serve public API: run/delete/status/shutdown/handles.

Capability parity: reference python/ray/serve/api.py (serve.run :691) +
_private/api.py serve_start — get-or-create controller actor, deploy application
graphs, proxy bring-up, handle acquisition.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import ray_tpu

from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, Deployment
from .handle import DeploymentHandle

_PROXY_NAME = "SERVE_PROXY"


@dataclasses.dataclass
class _HandleMarker:
    app_name: str
    deployment_name: str


def _get_or_create_controller():
    # every serve entry point keeps the head-side autoscaling loop alive (it
    # no-ops off the head process and when already running); the head-restart
    # reattach path restarts it independently (core/node.py)
    from .autoscaler import ensure_serve_autoscaler

    ensure_serve_autoscaler()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        # listen_for_change parks one call per connected handle/proxy process for
        # up to 10s; an unbounded "listen" concurrency group keeps any number of
        # parked listeners from starving deploy/reconcile/health RPCs, which run
        # on the default pool
        cls = ray_tpu.remote(num_cpus=0.1, name=CONTROLLER_NAME, lifetime="detached",
                             max_concurrency=16,
                             concurrency_groups={"listen": 0})(ServeController)
        handle = cls.remote()
        ray_tpu.get(handle.ping.remote())
        return handle


def start(http_options: Optional[Dict[str, Any]] = None,
          grpc_options: Optional[Dict[str, Any]] = None, **_compat) -> Optional[Dict[str, Any]]:
    """Bring up controller + ingress proxies (reference serve.start): HTTP
    always, gRPC when grpc_options is given (reference gRPCProxy). Returns
    {"grpc_port": N} when the gRPC ingress is up (port 0 = ephemeral bind)."""
    _get_or_create_controller()
    http_options = http_options or {}
    try:
        ray_tpu.get_actor(_PROXY_NAME)
    except ValueError:
        from .proxy import ProxyActor

        cls = ray_tpu.remote(num_cpus=0.1, name=_PROXY_NAME, lifetime="detached")(ProxyActor)
        proxy = cls.remote(http_options.get("host", "127.0.0.1"), http_options.get("port", 8000))
        ray_tpu.get(proxy.ready.remote())
    if grpc_options is not None:
        from .grpc_proxy import start_grpc_proxy

        _, port = start_grpc_proxy(
            grpc_options.get("host", "127.0.0.1"),
            grpc_options.get("port", 9000),
            grpc_options.get("grpc_servicer_functions"))
        return {"grpc_port": port}
    return None


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: str = "/",
    blocking: bool = False,
    _local_testing_mode: bool = False,
    **_compat,
) -> DeploymentHandle:
    """Deploy an application graph; returns the ingress handle (reference api.py:691).

    _local_testing_mode=True runs the whole graph in-process with no cluster
    (reference _private/local_testing_mode.py)."""
    from ray_tpu.usage import record_library_usage

    record_library_usage("serve")
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError("serve.run expects an Application (deployment.bind(...))")
    if _local_testing_mode:
        from .local_testing import run_local

        return run_local(target)
    controller = _get_or_create_controller()

    apps: list = []
    target._collect(apps)

    def encode(value):
        if isinstance(value, Application):
            return _HandleMarker(name, value.deployment.name)
        return value

    payload = []
    for bound in apps:
        payload.append({
            "name": bound.deployment.name,
            "serialized_init": {
                "target": bound.deployment._target,
                "args": tuple(encode(a) for a in bound.args),
                "kwargs": {k: encode(v) for k, v in bound.kwargs.items()},
            },
            "config": bound.deployment.config,
            "is_ingress": bound is target,
        })
    ray_tpu.get(controller.deploy_application.remote(name, route_prefix, payload))
    handle = DeploymentHandle(name, target.deployment.name)
    # wait until the ingress deployment has at least one running replica
    import time

    deadline = time.time() + 60
    while time.time() < deadline:
        info = ray_tpu.get(controller.get_deployment_info.remote(name, target.deployment.name))
        if info and info["num_running"] >= 1:
            break
        time.sleep(0.1)
    else:
        raise TimeoutError(f"app {name!r} failed to reach RUNNING within 60s: {info}")
    return handle


def delete(name: str, _blocking: bool = True) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name))


def status() -> Dict[str, Any]:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.status.remote())


def get_app_handle(name: str) -> DeploymentHandle:
    controller = _get_or_create_controller()
    st = ray_tpu.get(controller.status.remote())
    if name not in st:
        raise ValueError(f"no app named {name!r}")
    table = ray_tpu.get(controller.get_routing_table.remote())
    for info in table.values():
        if info["app"] == name:
            return DeploymentHandle(name, info["deployment"])
    raise ValueError(f"app {name!r} has no ingress")


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def shutdown() -> None:
    from .autoscaler import shutdown_serve_autoscaler
    from .handle import _reset_long_poll

    shutdown_serve_autoscaler()  # before the controller: no scale RPCs mid-kill
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass
    try:
        proxy = ray_tpu.get_actor(_PROXY_NAME)
        ray_tpu.kill(proxy)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass
    try:
        from .grpc_proxy import _GRPC_PROXY_NAME

        gproxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
        ray_tpu.get(gproxy.stop.remote())
        ray_tpu.kill(gproxy)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass
    _reset_long_poll()  # watches reference the controller we just killed
