"""HTTP proxy: per-node ingress routing requests to deployment handles.

Capability parity: reference python/ray/serve/_private/proxy.py (HTTPProxy :699,
ProxyActor :1021) — route-prefix matching, JSON request/response bridging to handles.
aiohttp replaces uvicorn (not baked into this image); the blocking handle call runs on
an executor thread so the event loop keeps accepting connections.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import json
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.util import telemetry

from .controller import CONTROLLER_NAME
from .handle import DeploymentHandle


def _observe_ttft(route: str, seconds: float) -> None:
    """Time-to-first-byte at the ingress: first stream chunk for SSE requests,
    the full response for unary ones — the p50/p99 rows in `ray-tpu status`
    and the SLO input for autoscaling."""
    telemetry.get_histogram(
        "serve_ttft_seconds", "HTTP ingress time-to-first-token/response",
        tag_keys=("route",)).observe(seconds, tags={"route": route})


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, Dict[str, Any]] = {}
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve_forever, daemon=True)
        self._thread.start()

    def ready(self) -> bool:
        self._ready.wait(timeout=30)
        return self._ready.is_set()

    def _refresh_routes(self) -> None:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes = ray_tpu.get(controller.get_routing_table.remote())

    def _match(self, path: str):
        best = None
        for prefix, info in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, info)
        return best

    def _serve_forever(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def handler(request: "web.Request") -> "web.Response":
            t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
            self._refresh_routes()
            m = self._match(request.path)
            if m is None:
                return web.Response(status=404, text=f"no route for {request.path}")
            prefix, info = m
            key = f"{info['app']}/{info['deployment']}"
            if key not in self._handles:
                self._handles[key] = DeploymentHandle(info["app"], info["deployment"])
            handle = self._handles[key]
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query)

            request_dict = {
                "path": request.path[len(prefix.rstrip("/")):] or "/",
                "method": request.method,
                "query": dict(request.query),
                "headers": dict(request.headers),
                "body": payload,
            }

            # streaming (reference proxy.py:699 ASGI streaming): OpenAI-style
            # {"stream": true} bodies or ?stream=1 run a streaming handle call
            # and forward chunks as they arrive (SSE-compatible)
            # truthiness, matching OpenAIRouter's gate — {"stream": 1} must not
            # desynchronize the proxy (non-stream) from the router (stream)
            wants_stream = (
                (isinstance(payload, dict) and bool(payload.get("stream")))
                or request.query.get("stream") in ("1", "true")
            )
            if wants_stream:
                # handle.remote() blocks on replica discovery (up to 30s) and
                # every next(g) blocks until the replica yields. Each stream
                # gets its OWN single-thread executor: a handful of slow or
                # idle streaming clients must not occupy the event loop's
                # default executor (min(32, cpus+4) threads — ~5 on a small
                # host), which also serves every non-streaming call.
                stream_exec = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="serve-sse")

                def start_stream():
                    return handle.options(method_name="__http__",
                                          stream=True).remote(request_dict)

                _end = object()

                def make_pull(g):
                    def pull():
                        try:
                            return next(g)
                        except StopIteration:
                            return _end
                    return pull

                gen = None
                try:
                    try:
                        gen = await loop.run_in_executor(stream_exec, start_stream)
                        pull = make_pull(gen)
                        first = await loop.run_in_executor(stream_exec, pull)
                        _observe_ttft(prefix,
                                      (time.perf_counter_ns() - t0_perf) / 1e9)
                        # "stream": true is an OpenAI convention; a deployment
                        # that returned one plain JSON value was not actually
                        # streaming — answer with ordinary JSON instead of a
                        # one-blob SSE body
                        if isinstance(first, (dict, list)):
                            second = await loop.run_in_executor(stream_exec, pull)
                            if second is _end:
                                return web.json_response(first)
                            pending = [first, second]
                        else:
                            pending = [] if first is _end else [first]
                    except Exception as e:  # noqa: BLE001 - surface as 500
                        return web.Response(status=500, text=repr(e))
                    resp = web.StreamResponse(
                        headers={"Content-Type": "text/event-stream",
                                 "Cache-Control": "no-cache"})
                    await resp.prepare(request)

                    async def write_chunk(chunk):
                        if isinstance(chunk, bytes):
                            await resp.write(chunk)
                        elif isinstance(chunk, str):
                            await resp.write(chunk.encode())
                        else:
                            await resp.write(json.dumps(chunk).encode() + b"\n")

                    try:
                        for chunk in pending:
                            await write_chunk(chunk)
                        while True:
                            chunk = await loop.run_in_executor(stream_exec, pull)
                            if chunk is _end:
                                break
                            await write_chunk(chunk)
                    except Exception as e:  # noqa: BLE001 — mid-stream: terminate body
                        # client gone or replica error: stop the producer so it
                        # releases engine resources (KV slots) early
                        if gen is not None:
                            stream_exec.submit(gen.close)
                            gen = None
                        try:
                            await resp.write(f"\nerror: {e!r}\n".encode())
                        except Exception:  # noqa: BLE001 — socket already closed
                            pass
                    await resp.write_eof()
                    if telemetry.enabled():
                        telemetry.complete(
                            "serve.http", "serve", t0_wall,
                            time.perf_counter_ns() - t0_perf,
                            route=prefix, method=request.method, stream=True)
                    return resp
                finally:
                    if gen is not None:
                        stream_exec.submit(gen.close)
                    stream_exec.shutdown(wait=False)

            def call():
                return handle.options(method_name="__http__").remote(request_dict).result()

            try:
                result = await loop.run_in_executor(None, call)
            except Exception as e:  # noqa: BLE001 - surface as 500
                return web.Response(status=500, text=repr(e))
            _observe_ttft(prefix, (time.perf_counter_ns() - t0_perf) / 1e9)
            if telemetry.enabled():
                telemetry.complete(
                    "serve.http", "serve", t0_wall,
                    time.perf_counter_ns() - t0_perf,
                    route=prefix, method=request.method, stream=False)
            from .asgi import RAW_RESPONSE_KEY

            if isinstance(result, dict) and result.get(RAW_RESPONSE_KEY):
                # ASGI deployments return verbatim status/headers/body; repeated
                # header names (multiple Set-Cookie) must survive, so build a
                # multidict rather than a plain dict
                from multidict import CIMultiDict

                hdrs = CIMultiDict()
                for k, v in result["headers"]:
                    if k.lower() != "content-length":
                        hdrs.add(k, v)
                return web.Response(status=result["status"], body=result["body"],
                                    headers=hdrs)
            if isinstance(result, (dict, list)):
                return web.json_response(result)
            if isinstance(result, bytes):
                return web.Response(body=result)
            return web.Response(text=str(result))

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        ssl_ctx = None
        from ray_tpu.config import CONFIG

        if CONFIG.serve_ingress_tls:
            from ray_tpu.core.tls_utils import ingress_ssl_context

            ssl_ctx = ingress_ssl_context()
        site = web.TCPSite(runner, self.host, self.port, ssl_context=ssl_ctx)
        loop.run_until_complete(site.start())
        self._ready.set()
        loop.run_forever()

    def stop(self) -> None:
        pass
